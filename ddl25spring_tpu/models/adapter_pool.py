"""Per-tenant LoRA adapter pool — KV-page discipline for adapter slots.

The multi-LoRA batcher (models/serving.py ``adapter_slots=N``) keeps one
``MultiLoRADense`` stack of N adapter slots in HBM next to the KV page
pool.  This module is the HOST-side bookkeeping for those slots, run
with exactly the ``kv_pool`` machinery so operators reason about one
residency model for both planes:

- slot 0 is RESERVED for the null adapter (all-zero factors — the
  bitwise base-model contract), like the pool's reserved null page;
- every in-flight stream holding a tenant's adapter REFCOUNTS its slot
  (``acquire``/``release``), so a busy adapter can never be evicted out
  from under a decode step;
- cold unpinned slots are evicted LRU when a new tenant needs a slot
  (``serving_adapter_evictions_total``), and an evicted tenant's return
  is a MISS (``serving_adapter_misses_total``) served by re-fetching the
  factors from the host-side store and re-installing them — the
  spill-pool park/resume story, one level up;
- ``pin``/``unpin`` exempt a tenant from eviction (the head-page pin).

The pool is jax-free (HOST_ONLY in the manifest): it decides WHICH slot
a tenant occupies; the batcher owns the device write
(``lora.install_adapter``).  :func:`adapter_bytes` is the analytic HBM
cost of the stacks — cross-checked against AOT argument bytes by
``tools/mem_estimate.py --adapter-pool`` — and feeds the shared-budget
sizing: the batcher shrinks its default KV page count by the pages the
stacks displace (``kv_pool.pages_displaced``).
"""

from __future__ import annotations

from .. import obs

NULL_ADAPTER = 0    # reserved slot: the all-zero null adapter


class AdapterPool:
    """Slot bookkeeping for one replica's adapter stacks.

    ``store`` maps ``tenant -> (adapter, scale, round_ix)`` and is the
    re-fetch source on a miss; it may be SHARED across replicas (the
    tenants plane passes one dict to every ``make_replica``).  The pool
    never copies adapter payloads — it hands them back to the batcher,
    which installs them on device.
    """

    def __init__(self, nr_slots: int, *, store: dict | None = None):
        if nr_slots < 2:
            raise ValueError(
                f"nr_slots={nr_slots}: need slot 0 (null) plus at least "
                "one tenant slot")
        self.nr_slots = nr_slots
        self.store: dict = store if store is not None else {}
        self._slot_of: dict = {}               # tenant -> slot
        self._tenant_of: dict[int, object] = {}  # slot -> tenant
        self._refs = [0] * nr_slots
        self._pinned: set[int] = set()
        self._clock = 0
        self._last_used = [0] * nr_slots       # LRU stamp per slot
        self.misses = 0
        self.evictions = 0
        self.installs = 0

    # -- host store ------------------------------------------------------

    def put(self, tenant, adapter, scale: float, round_ix=None) -> None:
        """(Re)register a tenant's factors in the host store.  A
        RESIDENT tenant's slot is NOT rewritten here — the caller
        decides whether to hot-swap in place (single-replica flows) or
        roll the new version through the rollout plane (fleets)."""
        if tenant == NULL_ADAPTER:
            raise ValueError("tenant 0 is the reserved null adapter")
        self.store[tenant] = (adapter, float(scale), round_ix)

    # -- residency -------------------------------------------------------

    def slot_of(self, tenant):
        """The tenant's resident slot, or None."""
        return self._slot_of.get(tenant)

    def resident(self, tenant) -> bool:
        return tenant in self._slot_of

    @property
    def resident_tenants(self):
        return sorted(self._slot_of, key=lambda t: self._slot_of[t])

    def seed(self, tenant, slot: int) -> None:
        """Mark a tenant resident WITHOUT an install — the factors are
        already in the params (a rollout-plane replica built from
        pre-stacked params).  Refcount starts at zero."""
        if not 0 < slot < self.nr_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._tenant_of or tenant in self._slot_of:
            raise ValueError(
                f"seed({tenant!r}, {slot}): slot or tenant already "
                "resident")
        self._slot_of[tenant] = slot
        self._tenant_of[slot] = tenant
        self._clock += 1
        self._last_used[slot] = self._clock

    def can_admit(self, tenant) -> bool:
        """Would ``acquire(tenant)`` succeed right now?  The batcher's
        admission gate — head-of-line waits on this exactly like it
        waits on free KV pages."""
        if tenant == NULL_ADAPTER or tenant in self._slot_of:
            return True
        return tenant in self.store and self._find_slot() is not None

    def acquire(self, tenant):
        """Take a stream's reference on ``tenant``'s slot.

        Returns ``(slot, entry)`` where ``entry`` is None for a
        residency hit and the ``(adapter, scale, round_ix)`` store entry
        when the caller must install the factors first (a miss — cold
        tenant, possibly after evicting another).  Returns ``None`` when
        no slot can be freed (every slot busy or pinned): the admission
        stays queued.  Tenant 0 needs no slot and no refcount."""
        if tenant == NULL_ADAPTER:
            return NULL_ADAPTER, None
        slot = self._slot_of.get(tenant)
        if slot is not None:
            self._refs[slot] += 1
            self._touch(slot)
            return slot, None
        if tenant not in self.store:
            raise KeyError(
                f"adapter_id {tenant!r} is not registered (put() it "
                "first)")
        slot = self._find_slot()
        if slot is None:
            return None
        old = self._tenant_of.pop(slot, None)
        if old is not None:
            del self._slot_of[old]
            self.evictions += 1
            obs.inc("serving_adapter_evictions_total")
        self.misses += 1
        obs.inc("serving_adapter_misses_total")
        self._slot_of[tenant] = slot
        self._tenant_of[slot] = tenant
        self._refs[slot] = 1
        self.installs += 1
        self._touch(slot)
        return slot, self.store[tenant]

    def release(self, tenant) -> None:
        """Drop one stream's reference (stream finished/evicted)."""
        if tenant == NULL_ADAPTER:
            return
        slot = self._slot_of.get(tenant)
        if slot is None or self._refs[slot] <= 0:
            raise ValueError(
                f"release({tenant!r}): tenant not resident or refcount "
                "already zero")
        self._refs[slot] -= 1

    def pin(self, tenant) -> None:
        slot = self._slot_of.get(tenant)
        if slot is None:
            raise ValueError(f"pin({tenant!r}): tenant not resident")
        self._pinned.add(slot)

    def unpin(self, tenant) -> None:
        slot = self._slot_of.get(tenant)
        if slot is not None:
            self._pinned.discard(slot)

    # -- internals -------------------------------------------------------

    def _touch(self, slot: int) -> None:
        self._clock += 1
        self._last_used[slot] = self._clock

    def _find_slot(self):
        """A free slot, else the LRU cold (refcount 0, unpinned)
        resident one, else None."""
        for s in range(1, self.nr_slots):
            if s not in self._tenant_of:
                return s
        cold = [s for s in self._tenant_of
                if self._refs[s] == 0 and s not in self._pinned]
        if not cold:
            return None
        return min(cold, key=lambda s: self._last_used[s])

    def describe(self) -> dict:
        return {
            "nr_slots": self.nr_slots,
            "resident": {t: s for t, s in sorted(self._slot_of.items(),
                                                 key=lambda kv: kv[1])},
            "refs": {s: r for s, r in enumerate(self._refs) if r},
            "pinned": sorted(self._pinned),
            "store_tenants": sorted(self.store),
            "misses": self.misses,
            "evictions": self.evictions,
            "installs": self.installs,
        }


def adapter_bytes(config, nr_slots: int | None = None, *,
                  itemsize: int = 4) -> int:
    """Analytic HBM bytes of the MultiLoRADense stacks for ``config``.

    Per dense site with shape ``(d_in, d_out)`` each slot costs
    ``rank * (d_in + d_out) * itemsize`` for its ``A``/``B`` factors
    plus ``itemsize`` for its scale entry.  The sites are the seven
    per-block matmuls (wq, wk, wv, wo, w1, w3, w2) plus ``lm_head`` —
    exactly where ``_dense_cls`` places the stacks.  Cross-checked
    leaf-exactly and against compiled argument bytes by
    ``tools/mem_estimate.py --adapter-pool``.
    """
    n = config.lora_slots if nr_slots is None else nr_slots
    r = config.lora_rank
    if n <= 0 or r <= 0:
        return 0
    d = config.dmodel
    kv = config.kv_heads * config.head_dim
    h = config.hidden_dim
    sites = [(d, d), (d, kv), (d, kv), (d, d),      # wq wk wv wo
             (d, h), (d, h), (h, d)] * config.nr_layers
    sites.append((d, config.vocab_size))            # lm_head
    per_slot = sum(r * (i + o) * itemsize for i, o in sites)
    return n * (per_slot + len(sites) * itemsize)
