"""Host-side fleet router over N ``ContinuousBatcher`` replicas.

The router owns request placement only; each replica keeps its own
queue, pool, admission control and compiled programs (which the
``_programs`` lru shares across same-shape replicas — N replicas compile
ONCE).  Placement is breaker-state + prefix-affinity + least-load +
SLO-slack (``serving_fleet.policy``); a replica that still rejects
(:class:`~ddl25spring_tpu.models.serving.AdmissionRejected` — queue
full, SLO, pool) triggers a bounded re-route to the next-ranked replica
through :func:`~ddl25spring_tpu.resilience.retry.retry_call`, reusing
the rejection's ``reason``/``retry_after_s`` for telemetry and for the
error the caller finally sees (the rejection with the SOONEST
``retry_after_s`` across the fleet).

Fault tolerance (``docs/RESILIENCE.md`` §9):

- **isolation** — ``step()`` steps each replica under its own
  try/except; one replica raising no longer kills the fleet step;
- **health** — pass ``health=FleetHealth(n)`` and every step feeds the
  per-replica breaker (``serving_fleet.health``); open replicas receive
  no placements, suspects are demoted, half-open admits one canary;
- **exactly-once failover** — a replica that raises from ``step()`` is
  dead for good (never stepped or placed again, so its in-flight work
  can never surface twice); every rid it owned is re-submitted to a
  surviving replica, re-prefilled from the original prompt plus the
  tokens already streamed (salvaged from the dead replica's slots), and
  the final stream is stitched so the caller sees no gap and no
  duplicate.  ``fail_replica``/``drain_replica``/``swap_replica`` give
  operators the same machinery for rolling restarts.

Autoscaling signals ride on ``obs``: per-replica queue-wait and
measured page-drain-rate gauges (``fleet_replica_queue_wait_s``,
``fleet_replica_drain_pps``) plus routing/failover counters — these are
the inputs a scaler needs to decide "add a replica" (queue wait growing
fleet-wide) vs "rebalance" (one replica hot) vs "replace" (breakers
opening).

Like ``policy``, this module never imports jax: rejections are matched
structurally (``reason``/``retry_after_s`` attributes) so the router —
and its tests — run with fake replicas in a jax-free process.
"""

from __future__ import annotations

import time
from collections import deque

from .. import obs
from ..resilience.retry import RetryError, retry_call
from . import policy

__all__ = ["FleetRouter", "NoReplicaAvailable"]


class _Rerouted(RuntimeError):
    """Internal: one replica rejected; carries the original exception so
    the retry loop can re-raise the real rejection when every candidate
    is exhausted (keeping the router import-independent of serving)."""

    def __init__(self, original):
        super().__init__(str(original))
        self.original = original


class NoReplicaAvailable(RuntimeError):
    """Every replica is dead, draining, or breaker-excluded: there is no
    candidate to even ASK.  Structurally a rejection (``reason`` +
    ``retry_after_s``) so backpressure-aware clients handle it exactly
    like admission rejection — back off and retry."""

    def __init__(self, message: str, retry_after_s: float = 0.05):
        super().__init__(message)
        self.reason = "no_replica"
        self.retry_after_s = retry_after_s


def _is_rejection(e: BaseException) -> bool:
    return hasattr(e, "reason") and hasattr(e, "retry_after_s")


def _emitted_total(replica) -> int:
    """Tokens currently streamed into active slots — the step-progress
    signal the health tracker compares across one ``step()``."""
    return sum(len(getattr(sl, "emitted", ()))
               for sl in getattr(replica, "slots", ()))


def _slot_partials(replica):
    """Fallback salvage reader for replicas without ``partial_tokens``
    (the ``FaultyReplica`` chaos wrapper provides its own): streamed
    host-int tokens per active slot — in streaming mode a batcher's
    ``emitted`` lists hold exactly the tokens the caller already saw."""

    def read() -> dict:
        out: dict = {}
        for sl in getattr(replica, "slots", ()):
            rid = getattr(sl, "request_id", None)
            if rid is None:
                continue
            out[rid] = [t for t in getattr(sl, "emitted", ())
                        if isinstance(t, int)]
        return out

    return read


class _FleetPoolView:
    """Duck-typed pool facade so :func:`loadgen.replay` can read fleet
    page residency: the peak is summed per replica (each pool peaks
    independently — the sum is the fleet's resident-KV high-water
    bound)."""

    def __init__(self, replicas):
        self._replicas = replicas

    @property
    def pages_peak(self) -> int:
        return sum(r._pool.pages_peak for r in self._replicas
                   if getattr(r, "_pool", None) is not None)

    @property
    def pages_in_use(self) -> int:
        return sum(r._pool.pages_in_use for r in self._replicas
                   if getattr(r, "_pool", None) is not None)


class FleetRouter:
    """Route requests over ``replicas`` (each a ``ContinuousBatcher`` —
    or anything with its submit/step/in_flight surface).

    ``max_reroutes`` bounds how many ADDITIONAL replicas a rejected
    request may try (default: all of them).  ``affinity_window`` is the
    prompt-head length used for the router's recency affinity map —
    requests sharing a head route to the replica that last served one,
    where its KV pages are warmest; the map is LRU-bounded at
    ``affinity_cap`` heads so a long-lived service cannot leak memory
    through prompt diversity.  ``trace_cap`` optionally bounds
    ``routing_trace`` the same way (default ``None`` keeps the full
    trace — the bit-identity replay contract needs it).  ``health`` is
    an optional :class:`~ddl25spring_tpu.serving_fleet.health.FleetHealth`;
    without one the router behaves exactly as before (no breaker, but
    step isolation and failover still apply).  Exposes the same
    ``submit``/``step``/``drain``/``in_flight`` surface as a single
    batcher, so ``loadgen.replay`` and ``saturation_sweep`` drive a
    fleet unchanged.
    """

    def __init__(self, replicas, *, max_reroutes: int | None = None,
                 affinity_window: int = 16, affinity_cap: int = 4096,
                 trace_cap: int | None = None, health=None):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        if max_reroutes is not None and max_reroutes < 0:
            raise ValueError(
                f"max_reroutes must be >= 0, got {max_reroutes}")
        if affinity_cap < 1:
            raise ValueError(
                f"affinity_cap must be >= 1, got {affinity_cap}")
        self.replicas = replicas
        for i, r in enumerate(replicas):
            try:
                # request traces tag decode chunks with the replica that
                # produced them; fake/frozen replicas may refuse the attr
                r._replica_ix = i
            except Exception:
                pass
        self.max_reroutes = (len(replicas) - 1 if max_reroutes is None
                             else max_reroutes)
        self.affinity_window = affinity_window
        self.affinity_cap = affinity_cap
        self.health = health
        self._affinity: dict = {}   # prompt head -> last replica (LRU)
        self._canary: set = set()   # canary slots (rollout plane)
        self._owner: dict = {}      # in-flight rid -> replica index
        self._requests: dict = {}   # rid -> (prompt, budget, deadline_s)
        self._salvaged: dict = {}   # failed-over rid -> tokens replayed
        self._orphans: list = []    # [(rid, salvaged, kind)] awaiting place
        self._dead: set = set()     # replica indices never used again
        self._draining: set = set()  # no NEW placements (rolling restart)
        self.routing_trace = (deque(maxlen=trace_cap)
                              if trace_cap is not None else [])
        self.stats = {"routed": 0, "rerouted": 0, "rejected": 0,
                      "rerouted_by_reason": {}, "rejected_by_reason": {},
                      "failed_over": 0, "failover_tokens_replayed": 0,
                      "replicas_failed": 0}

    # -- loadgen duck-type surface (drive a fleet like one batcher) ------

    @property
    def max_batch(self) -> int:
        return max(r.max_batch for r in self.replicas)

    @property
    def _paged(self) -> bool:
        return any(getattr(r, "_paged", False) for r in self.replicas)

    @property
    def _queue(self) -> list:
        return [q for i, r in enumerate(self.replicas)
                if i not in self._dead for q in r._queue]

    @property
    def _pool(self) -> _FleetPoolView:
        return _FleetPoolView(self.replicas)

    @property
    def in_flight(self) -> int:
        """Work the fleet still owes: live replicas' in-flight plus
        orphans awaiting re-placement.  Dead replicas are excluded —
        their in-flight can never finish and would wedge ``drain``."""
        return (sum(r.in_flight for i, r in enumerate(self.replicas)
                    if i not in self._dead)
                + len(self._orphans))

    # -- routing ---------------------------------------------------------

    def _head_key(self, prompt) -> tuple:
        return tuple(int(t) for t in list(prompt)[:self.affinity_window])

    def _note_affinity(self, head: tuple, ix: int) -> None:
        self._affinity.pop(head, None)
        self._affinity[head] = ix
        while len(self._affinity) > self.affinity_cap:
            self._affinity.pop(next(iter(self._affinity)))

    def _eligible(self) -> list:
        """Replica indices that may receive a NEW placement now: alive,
        not draining, and (with a health tracker) breaker-admitted."""
        return [i for i in range(len(self.replicas))
                if i not in self._dead and i not in self._draining
                and (self.health is None or self.health.admits(i))]

    def _health_state(self, i: int) -> str:
        return "healthy" if self.health is None else self.health.state(i)

    def assignments(self) -> dict:
        """replica index -> [rid, ...] in routed order (the pinned trace
        the bit-identity contract replays per replica).  A failed-over
        rid appears once per placement — original then failover."""
        out: dict = {i: [] for i in range(len(self.replicas))}
        for rid, ix in self.routing_trace:
            out[ix].append(rid)
        return out

    def submit(self, rid, prompt, max_new_tokens: int,
               deadline_s: float | None = None, adapter_id: int = 0) -> int:
        """Route and submit one request; returns the replica index it
        landed on.  Raises the best (soonest-retry) rejection when every
        candidate replica rejected, or :class:`NoReplicaAvailable` when
        the breaker/drain state leaves nothing to ask.

        ``adapter_id`` names the request's tenant (multi-LoRA replicas);
        placement then prefers replicas whose adapter pool already holds
        the tenant's factors (tenant affinity,
        ``fleet_tenant_affinity_hits_total``) — a miss forces the target
        to re-fetch the factors and possibly evict another tenant's."""
        if rid in self._owner or rid in self._requests:
            raise ValueError(f"request id {rid!r} already in flight")
        adapter_id = int(adapter_id)
        head = self._head_key(prompt)
        eligible = self._eligible()
        if not eligible:
            self.stats["rejected"] += 1
            by = self.stats["rejected_by_reason"]
            by["no_replica"] = by.get("no_replica", 0) + 1
            obs.inc("fleet_rejected_total", reason="no_replica")
            raise NoReplicaAvailable(
                f"no replica can accept request {rid!r}: "
                f"{len(self._dead)} dead, {len(self._draining)} "
                "draining, rest breaker-excluded")
        snaps = [policy.snapshot_replica(
            i, self.replicas[i], prompt, int(max_new_tokens),
            affinity_hit=self._affinity.get(head) == i,
            adapter_id=adapter_id,
            health_state=self._health_state(i),
            canary=i in self._canary,
        ) for i in eligible]
        hit_of = {s.index: s.tenant_hit for s in snaps}
        order = policy.rank_replicas(snaps)
        state = {"attempt": 0}
        rejections: list = []

        def attempt():
            ix = order[state["attempt"]]
            state["attempt"] += 1
            try:
                if adapter_id:
                    self.replicas[ix].submit(rid, prompt, max_new_tokens,
                                             deadline_s=deadline_s,
                                             adapter_id=adapter_id)
                else:
                    # null-adapter traffic uses the pre-tenant call shape,
                    # so fake/frozen replicas without the kwarg keep working
                    self.replicas[ix].submit(rid, prompt, max_new_tokens,
                                             deadline_s=deadline_s)
            except Exception as e:
                if not _is_rejection(e):
                    raise
                rejections.append(e)
                raise _Rerouted(e) from e
            return ix

        try:
            ix = retry_call(
                attempt, retries=min(self.max_reroutes, len(order) - 1),
                base_delay_s=0.0, jitter=0.0, retry_on=(_Rerouted,),
                label="fleet.route",
            )
        except (_Rerouted, RetryError):
            # every candidate rejected: count each rejection under its
            # reason (the re-route counter only sees rejections that had
            # an onward candidate), then surface the rejection the
            # caller can act on soonest (min retry_after_s)
            self.stats["rejected"] += 1
            by = self.stats["rejected_by_reason"]
            for e in rejections:
                by[e.reason] = by.get(e.reason, 0) + 1
                obs.inc("fleet_rejected_total", reason=e.reason)
            raise min(rejections, key=lambda e: e.retry_after_s) from None
        for e in rejections:
            # count only the rejections that caused an onward re-route
            by = self.stats["rerouted_by_reason"]
            by[e.reason] = by.get(e.reason, 0) + 1
            obs.inc("fleet_rerouted_total", reason=e.reason)
        self.stats["rerouted"] += len(rejections)
        self.stats["routed"] += 1
        obs.inc("fleet_routed_total", replica=str(ix))
        if adapter_id and hit_of.get(ix):
            # the request landed where its adapter already lives — the
            # tenant-affinity win the ranking key exists to produce
            obs.inc("fleet_tenant_affinity_hits_total")
        rt = obs.reqtrace()
        if rt is not None:
            rt.note(rid, "placed", replica=ix, reroutes=len(rejections),
                    tenant=adapter_id)
        fr = obs.flight()
        if fr is not None:
            fr.record("router", "placed", rid=repr(rid), replica=ix,
                      reroutes=len(rejections))
        self._note_affinity(head, ix)
        self._owner[rid] = ix
        self._requests[rid] = (tuple(int(t) for t in list(prompt)),
                               int(max_new_tokens), deadline_s, adapter_id)
        self.routing_trace.append((rid, ix))
        if self.health is not None:
            self.health.note_placed(ix, rid)
        return ix

    # -- stepping --------------------------------------------------------

    def _publish_gauges(self):
        if not obs.enabled():
            return
        for i, r in enumerate(self.replicas):
            if i in self._dead:
                continue
            est = getattr(r, "_chunk_s", 0.0)
            mb = max(1, int(getattr(r, "max_batch", 1)))
            wait = est * (len(r._queue) / mb)
            obs.set_gauge("fleet_replica_queue_wait_s", wait,
                          replica=str(i))
            obs.set_gauge("fleet_replica_drain_pps",
                          getattr(r, "_drain_pps", 0.0), replica=str(i))

    def _absorb(self, ix: int, out: dict) -> dict:
        """Book-keep one replica's finished requests: release ownership,
        stitch salvaged failover tokens back onto the front of the
        stream, and feed the breaker (a clean finish is the half-open
        canary's recovery proof; a deadline eviction is not)."""
        res: dict = {}
        for rid, toks in out.items():
            self._owner.pop(rid, None)
            self._requests.pop(rid, None)
            if self.health is not None:
                if getattr(toks, "status", "ok") == "ok":
                    self.health.note_finished(ix, rid)
                else:
                    self.health.note_evicted(ix, rid)
            sal = self._salvaged.pop(rid, None)
            if sal:
                merged = list(sal) + list(toks)
                status = getattr(toks, "status", None)
                toks = (type(toks)(merged, status) if status is not None
                        else merged)
            rt = obs.reqtrace()
            if rt is not None:
                # "deliver" (not "finish" — the batcher notes that): the
                # stream as the CALLER sees it, salvage stitched back on
                rt.note(rid, "deliver", replica=ix, tokens=len(toks),
                        status=getattr(toks, "status", "ok"),
                        stitched=len(sal) if sal else 0)
            res[rid] = toks
        return res

    def _fail_over(self, ix: int, exc) -> dict:
        """Replica ``ix`` is dead (raised from ``step()`` or was failed
        by an operator): never step or place on it again, salvage the
        tokens its slots already streamed, and orphan every rid it
        owned for re-placement.  Returns requests that finished DURING
        the failover (salvage already covered their whole budget)."""
        self._dead.add(ix)
        self._draining.discard(ix)
        # purge stale prefix affinity NOW: post-failover placements must
        # not chase prefix hits into a cache that no longer exists (and
        # the affinity_hit telemetry would lie for every one that did)
        self._affinity = {h: r for h, r in self._affinity.items()
                          if r != ix}
        self.stats["replicas_failed"] += 1
        kind = getattr(exc, "kind", None) or "replica_crash"
        obs.inc("fleet_replica_failed_total", kind=kind,
                replica=str(ix))
        if self.health is not None:
            self.health.record_crash(ix)
        partials: dict = {}
        getter = getattr(self.replicas[ix], "partial_tokens",
                         _slot_partials(self.replicas[ix]))
        try:
            partials = getter()
        except Exception:
            partials = {}   # the host side died too; replay from 0
        rt = obs.reqtrace()
        for rid, owner in list(self._owner.items()):
            if owner != ix:
                continue
            del self._owner[rid]
            if self.health is not None:
                self.health.note_evicted(ix, rid)
            # a second failover must keep the FIRST failover's salvage:
            # the dying replica only ever streamed the post-salvage tail
            salvaged = (self._salvaged.pop(rid, [])
                        + [int(t) for t in partials.get(rid, ())])
            if rt is not None:
                rt.note(rid, "salvage", replica=ix, kind=kind,
                        tokens=len(salvaged))
            self._orphans.append((rid, salvaged, kind))
        fr = obs.flight()
        if fr is not None:
            fr.record("router", "failover", replica=ix, fault=kind,
                      orphans=[repr(r) for r, _s, _k in self._orphans])
        # the event (not just the counter) is what trips the flight
        # recorder's dump — emit AFTER salvage so the dump carries the
        # orphan set this failure created
        obs.event("fleet.replica_failed", replica=ix, kind=kind,
                  orphans=sum(1 for _r, _s, k in self._orphans
                              if k == kind))
        return self._retry_orphans()

    def _retry_orphans(self) -> dict:
        """Re-place orphaned requests on surviving replicas.  Placement
        is best-effort per step — an orphan that cannot place now (all
        candidates rejecting or breaker-excluded) stays queued and is
        retried next ``step()``."""
        if not self._orphans:
            return {}
        if all(i in self._dead for i in range(len(self.replicas))):
            raise RuntimeError(
                f"all {len(self.replicas)} replicas dead with "
                f"{len(self._orphans)} requests orphaned — nothing "
                "left to fail over to")
        finished: dict = {}
        still: list = []
        for rid, salvaged, kind in self._orphans:
            prompt, budget, deadline_s, adapter_id = self._requests[rid]
            remaining = budget - len(salvaged)
            if remaining <= 0:
                # the dead replica had already streamed the full budget;
                # the salvage IS the answer
                self._requests.pop(rid, None)
                finished[rid] = list(salvaged)
                self._count_failover(kind, len(salvaged))
                continue
            ix = self._place_orphan(rid, prompt, salvaged, remaining,
                                    deadline_s, adapter_id)
            if ix is None:
                still.append((rid, salvaged, kind))
                continue
            self._count_failover(kind, len(salvaged))
        self._orphans = still
        return finished

    def _count_failover(self, kind: str, nr_replayed: int) -> None:
        self.stats["failed_over"] += 1
        self.stats["failover_tokens_replayed"] += nr_replayed
        obs.inc("fleet_failover_total", kind=kind)
        if nr_replayed:
            obs.inc("fleet_failover_tokens_replayed_total", nr_replayed)

    def _place_orphan(self, rid, prompt, salvaged, remaining: int,
                      deadline_s, adapter_id: int = 0) -> int | None:
        """Try to land one orphan on a surviving replica.  Preferred
        form: continuation — re-prefill ``prompt + salvaged`` and decode
        only the remaining budget (the salvaged tokens are replayed
        through prefill, not re-decoded).  When the continuation does
        not fit the target's prefill window, fall back to a full
        resubmit (the whole stream re-decodes; greedy decode makes it
        identical)."""
        eligible = self._eligible()
        if not eligible:
            return None
        snaps = [policy.snapshot_replica(
            i, self.replicas[i], prompt, remaining,
            affinity_hit=False, adapter_id=adapter_id,
            health_state=self._health_state(i),
            canary=i in self._canary,
        ) for i in eligible]
        for ix in policy.rank_replicas(snaps):
            r = self.replicas[ix]
            pw = getattr(r, "prefill_width", None)
            cont = tuple(prompt) + tuple(salvaged)
            try_cont = bool(salvaged) and (pw is None
                                           or len(cont) <= int(pw))
            kw = {"adapter_id": adapter_id} if adapter_id else {}
            try:
                if try_cont:
                    r.submit(rid, list(cont), remaining,
                             deadline_s=deadline_s, **kw)
                    self._salvaged[rid] = list(salvaged)
                else:
                    # full replay: drop the salvage, re-decode everything
                    r.submit(rid, list(prompt),
                             remaining + len(salvaged),
                             deadline_s=deadline_s, **kw)
                    self._salvaged.pop(rid, None)
            except Exception as e:
                if not _is_rejection(e):
                    raise
                continue
            rt = obs.reqtrace()
            if rt is not None:
                rt.note(rid, "replay", replica=ix,
                        mode="continuation" if try_cont else "full",
                        replayed=len(salvaged))
            fr = obs.flight()
            if fr is not None:
                fr.record("router", "replay", rid=repr(rid), replica=ix,
                          mode="continuation" if try_cont else "full",
                          replayed=len(salvaged))
            self._owner[rid] = ix
            self.routing_trace.append((rid, ix))
            if self.health is not None:
                self.health.note_placed(ix, rid)
            return ix
        return None

    def step(self) -> dict:
        """Step every live replica with work in flight; returns the
        merged ``{rid: tokens}`` of everything that finished this step.
        A replica raising is isolated: it is marked dead, its requests
        fail over, and the step continues with the survivors."""
        if self.health is not None:
            self.health.tick()
        finished: dict = {}
        for i, r in enumerate(self.replicas):
            if i in self._dead:
                continue
            pre = r.in_flight
            if not pre:
                continue
            em0 = _emitted_total(r) if self.health is not None else 0
            t0 = time.perf_counter()
            try:
                out = r.step()
            except Exception as e:
                if _is_rejection(e):
                    raise   # an admission error here is a router bug
                finished.update(self._fail_over(i, e))
                continue
            if self.health is not None:
                # progress = finishes + net new streamed tokens: a
                # streaming batcher returns {} mid-decode, so finishes
                # alone would strike every healthy long request
                progress = len(out) + max(0, _emitted_total(r) - em0)
                self.health.record_step(
                    i, time.perf_counter() - t0, progress, pre,
                    drain_pps=getattr(r, "_drain_pps", None))
            finished.update(self._absorb(i, out))
        if self._orphans:
            finished.update(self._retry_orphans())
        self._publish_gauges()
        obs.record_samples()
        return finished

    def drain(self, *, timeout_s: float | None = None) -> dict:
        """step() until the fleet is idle (optionally bounded).  On
        timeout the raised ``TimeoutError`` carries everything that DID
        finish as ``.partial`` so callers salvage completed requests."""
        t0 = time.perf_counter()
        out: dict = {}
        while self.in_flight:
            out.update(self.step())
            if (timeout_s is not None
                    and time.perf_counter() - t0 > timeout_s):
                err = TimeoutError(
                    f"fleet drain exceeded {timeout_s}s with "
                    f"{self.in_flight} requests in flight")
                err.partial = out
                raise err
        return out

    # -- operator surface (rolling restart / manual failover) -----------

    def fail_replica(self, i: int) -> dict:
        """Operator-initiated failover: treat replica ``i`` as dead NOW
        (exactly the path a ``step()`` crash takes) and migrate its
        in-flight requests.  Returns any that finished immediately
        (salvage already covered their budget)."""
        if not 0 <= i < len(self.replicas):
            raise ValueError(f"no replica {i}")
        if i in self._dead:
            return {}
        return self._fail_over(i, None)

    def begin_drain(self, i: int) -> None:
        """Non-blocking half of :meth:`drain_replica`: replica ``i``
        stops receiving new placements NOW, but the caller keeps
        stepping the fleet itself (the rollout controller's tick loop
        does this so live traffic flows while the replica empties).
        No-op on a dead replica; :meth:`end_drain` or
        :meth:`swap_replica` clears the mark."""
        if not 0 <= i < len(self.replicas):
            raise ValueError(f"no replica {i}")
        if i not in self._dead:
            self._draining.add(i)

    def end_drain(self, i: int) -> None:
        """Re-open replica ``i`` for placements (a drain that was
        abandoned rather than completed by a swap)."""
        self._draining.discard(i)

    def drain_replica(self, i: int, *,
                      timeout_s: float | None = None) -> dict:
        """Graceful drain for a rolling restart: replica ``i`` receives
        no new placements, and the fleet steps until its in-flight work
        completes — zero requests dropped.  Returns everything that
        finished fleet-wide during the drain; the replica is left marked
        draining (``swap_replica`` clears it).

        Timeout contract: on ``timeout_s`` expiry the raised
        ``TimeoutError`` carries everything that DID finish as
        ``.partial``, and replica ``i`` is left *draining with work
        still in flight* — the drain made no destructive move, so the
        caller chooses the recovery: keep stepping (the work is still
        progressing), ``end_drain(i)`` to abandon the restart, or
        ``fail_replica(i)`` to salvage-and-failover the stragglers
        exactly-once (what the rollout controller's tick-budgeted drain
        does — merge ``.partial`` with the failover's returns)."""
        if not 0 <= i < len(self.replicas):
            raise ValueError(f"no replica {i}")
        if i in self._dead:
            return {}
        self._draining.add(i)
        t0 = time.perf_counter()
        out: dict = {}
        while i not in self._dead and self.replicas[i].in_flight:
            out.update(self.step())
            if (timeout_s is not None
                    and time.perf_counter() - t0 > timeout_s):
                err = TimeoutError(
                    f"drain of replica {i} exceeded {timeout_s}s with "
                    f"{self.replicas[i].in_flight} requests in flight")
                err.partial = out
                raise err
        return out

    def swap_replica(self, i: int, replica) -> None:
        """Replace replica ``i`` (dead or drained) with a fresh one and
        re-open it for placement.  Refuses to discard in-flight work —
        ``drain_replica``/``fail_replica`` first.  The old replica's
        prefix-affinity entries are purged (the new replica's cache is
        cold — a stale hit would route into nothing) and its breaker
        history is reset."""
        if not 0 <= i < len(self.replicas):
            raise ValueError(f"no replica {i}")
        if i not in self._dead and self.replicas[i].in_flight:
            raise ValueError(
                f"replica {i} still has {self.replicas[i].in_flight} "
                "requests in flight — drain_replica() or "
                "fail_replica() first")
        self.replicas[i] = replica
        try:
            # decode chunks must trace as THIS slot (same best-effort
            # stamp the ctor applies; fake/frozen replicas may refuse)
            replica._replica_ix = i
        except Exception:
            pass
        self._dead.discard(i)
        self._draining.discard(i)
        self._affinity = {h: r for h, r in self._affinity.items()
                          if r != i}
        if self.health is not None:
            self.health.reset(i)

    # -- canary marking (rollout plane) ----------------------------------

    def mark_canary(self, i: int) -> None:
        """Flag replica ``i`` as a rollout canary: the policy PREFERS it
        among healthy feasible replicas so the canary window actually
        collects evidence (a canary that sees no traffic proves
        nothing); rejections re-route onward as usual, so preference
        never costs a request."""
        if not 0 <= i < len(self.replicas):
            raise ValueError(f"no replica {i}")
        self._canary.add(i)

    def clear_canary(self, i: int) -> None:
        self._canary.discard(i)

    def apply_scaling_hint(self, desired: int, *,
                           timeout_s: float | None = None) -> dict:
        """Consume an autoscaling signal (``AutoscalePolicy.observe``'s
        desired replica count).  Surplus replicas are drained through
        the rolling-restart path — emptiest first, so the drain is
        cheap and placement shifts to the survivors; a deficit is only
        *reported* (``deficit`` > 0 means under-provisioned: creating
        replicas needs compiled programs the router cannot conjure).
        Drained replicas stay draining until ``swap_replica``."""
        desired = max(1, int(desired))
        active = [i for i in range(len(self.replicas))
                  if i not in self._dead and i not in self._draining]
        report = {"desired": desired, "active": len(active),
                  "drained": [], "deficit": 0, "finished": {}}
        if desired < len(active):
            order = sorted(active,
                           key=lambda i: (self.replicas[i].in_flight, i))
            for i in order[:len(active) - desired]:
                report["finished"].update(
                    self.drain_replica(i, timeout_s=timeout_s))
                report["drained"].append(i)
                obs.inc("fleet_autoscale_drained_total", replica=str(i))
        elif desired > len(active):
            report["deficit"] = desired - len(active)
            obs.event("fleet.autoscale_deficit", desired=desired,
                      active=len(active),
                      deficit=report["deficit"])
        return report
