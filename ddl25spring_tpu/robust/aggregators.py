"""Byzantine-robust aggregators.

The reference course plans an attacks & defenses part (lab/README.md:13-16)
but ships no code for it; the only hook is the FedAvg server-side aggregation
point (hfl_complete.py:377-383).  These are jit-compiled pure functions over
the stacked client-update pytree, pluggable into ``make_fl_round``'s
``aggregator=`` argument (fl/engine.py).

All aggregators share the signature ``agg(stacked_updates, weights, key) ->
update`` where ``stacked_updates`` has a leading client axis of size m and
``weights`` are the n_k-normalized sample weights (ignored by the robust
aggregators, which assume adversarial counts can't be trusted).

References (public algorithms):
- Krum / multi-Krum: Blanchard et al., "Machine Learning with Adversaries:
  Byzantine Tolerant Gradient Descent", NeurIPS 2017.
- Coordinate-wise trimmed mean / median: Yin et al., "Byzantine-Robust
  Distributed Learning: Towards Optimal Statistical Rates", ICML 2018.
- Consensus-weighted aggregation: agreement-based adaptive weighting in the
  spirit of Alkhulaifi et al., "Adaptive Consensus Gradients Aggregation
  for Scaled Distributed Training", 2024 (PAPERS.md) — weights derive from
  each update's alignment with the consensus direction rather than from
  client-reported sample counts.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..utils.trees import tree_weighted_mean


def _stack_to_matrix(stacked, upcast: bool = True):
    """Flatten a stacked pytree (m, ...) into an (m, D) matrix plus a
    function mapping a (D,) vector back to one update pytree.

    ``upcast=False`` keeps reduced-precision stacks in their storage dtype
    for consumers that upcast tile-by-tile themselves (the pairwise
    distance kernels) — everyone else gets f32, because pairwise distances
    and sorted means must accumulate in f32 or selection becomes
    tie-unstable."""
    leaves = jax.tree.leaves(stacked)
    m = leaves[0].shape[0]
    mat = jnp.concatenate([leaf.reshape(m, -1) for leaf in leaves], axis=1)
    if upcast and mat.dtype in (jnp.bfloat16, jnp.float16):
        mat = mat.astype(jnp.float32)

    treedef = jax.tree.structure(stacked)
    shapes = [leaf.shape[1:] for leaf in leaves]
    sizes = [math.prod(s) for s in shapes]
    offsets = [0]
    for s in sizes:
        offsets.append(offsets[-1] + s)

    def unflatten(vec):
        parts = [
            vec[offsets[i]:offsets[i + 1]].reshape(shapes[i])
            for i in range(len(sizes))
        ]
        return jax.tree.unflatten(treedef, parts)

    return mat, unflatten


def _sq_dists(mat, impl: str):
    """All-pairs squared distances via :mod:`..ops.pairwise` (Gram identity
    ``‖a-b‖² = ‖a‖² + ‖b‖² - 2·a·b``, clamped at zero against round-off) —
    one (m, m) matmul instead of the naive (m, m, D) broadcast, so the
    distance pass peaks at O(m² + m·D) instead of O(m²·D), and on TPU the
    tiled Pallas kernel drops the m·D term to m·D_tile.  Imported lazily so
    robust rules don't pull jax.experimental.pallas into processes that
    never score a distance (the ops/__init__ discipline)."""
    from ..ops import pairwise

    return pairwise.pairwise_sq_dists(mat, impl=impl)


def weighted_mean(stacked, weights, key=None):
    """The plain FedAvg aggregation (reference hfl_complete.py:377-378)."""
    return tree_weighted_mean(stacked, weights)


def coordinate_median(stacked, weights=None, key=None):
    """Coordinate-wise median over the client axis."""
    mat, unflatten = _stack_to_matrix(stacked)
    return unflatten(jnp.median(mat, axis=0))


def make_trimmed_mean(trim_ratio: float):
    """Coordinate-wise mean after dropping the ``trim_ratio`` fraction of
    smallest and largest values in every coordinate."""

    def trimmed_mean(stacked, weights=None, key=None):
        mat, unflatten = _stack_to_matrix(stacked)
        m = mat.shape[0]
        k = int(trim_ratio * m)
        if 2 * k >= m:
            raise ValueError(f"trim_ratio {trim_ratio} removes all {m} clients")
        s = jnp.sort(mat, axis=0)
        kept = s[k : m - k] if k > 0 else s
        return unflatten(jnp.mean(kept, axis=0))

    return trimmed_mean


def make_consensus(nr_iterations: int = 2, temperature: float = 4.0):
    """Adaptive consensus-weighted mean: seed the consensus direction from
    the coordinate-wise median (a mean seed is unsafe — a scaled sign-flip
    coalition can cancel or invert it), then re-weight every client by
    (softmax-sharpened, non-negative) cosine alignment with the current
    consensus and iterate.

    Clients pulling against the consensus direction (sign-flip attackers,
    heavy label-flip) get weight ~0 without any Byzantine-count parameter —
    the practical advantage over Krum/trimmed-mean, which must be told f.
    Gradient-direction agreement is the robust signal; magnitudes and
    client-reported sample counts are never trusted.

    Meant for GRADIENT-type updates (FedSgdGradientServer, DP gradients),
    where direction carries the signal.  FedAvg-style weight vectors all
    point along the shared parameters, so their cosines are ~1 for honest
    and Byzantine clients alike — use Krum/trimmed-mean/median there.
    """

    def consensus(stacked, weights=None, key=None):
        from ..ops import pairwise

        mat, unflatten = _stack_to_matrix(stacked)
        norms = pairwise.row_norms(mat)[:, None] + 1e-12
        unit = mat / norms
        # robust anchor: a scaled sign-flip attack can cancel (or invert)
        # the uniform mean, making a mean-seeded iteration lock onto the
        # attackers; the coordinate-wise median survives any <50% coalition
        center = jnp.median(mat, axis=0)
        for _ in range(nr_iterations):
            center = center / (jnp.linalg.norm(center) + 1e-12)
            cos = unit @ center                       # (m,) in [-1, 1]
            w = jax.nn.softmax(temperature * cos)
            w = jnp.where(cos > 0.0, w, 0.0)          # hard-zero opposers
            w = w / (jnp.sum(w) + 1e-12)
            center = w @ mat
        return unflatten(center)

    return consensus


def make_krum(nr_byzantine: int, nr_selected: int = 1,
              pairwise_impl: str = "auto"):
    """(multi-)Krum: score each update by the sum of its m - f - 2 smallest
    squared distances to the other updates; keep the ``nr_selected``
    best-scoring updates and average them (``nr_selected=1`` is classic Krum).

    ``pairwise_impl`` selects the distance-pass backend (see
    ``ops.pairwise``): ``auto`` compiles the tiled Pallas kernel on TPU and
    the XLA Gram path elsewhere; reduced-precision stacks stay in storage
    dtype until the kernel's per-tile upcast.
    """

    def krum(stacked, weights=None, key=None):
        mat, unflatten = _stack_to_matrix(stacked, upcast=False)
        m = mat.shape[0]
        nr_neighbors = m - nr_byzantine - 2
        if nr_neighbors < 1:
            raise ValueError(
                f"krum needs m - f - 2 >= 1 (m={m}, f={nr_byzantine})"
            )
        sq = _sq_dists(mat, pairwise_impl)
        sq = sq + jnp.diag(jnp.full(m, jnp.inf))  # exclude self-distance
        neighbor_d = jnp.sort(sq, axis=1)[:, :nr_neighbors]
        scores = jnp.sum(neighbor_d, axis=1)
        chosen = jnp.argsort(scores)[:nr_selected]
        # only the selected rows get the f32 upcast (the full-matrix copy
        # is exactly what the tiled distance pass avoided)
        return unflatten(jnp.mean(mat[chosen].astype(jnp.float32), axis=0))

    # telemetry hook: marks this rule as distance-based so the round loop
    # can account the pass's bytes (obs gauge fl_aggregator_dist_bytes)
    krum.pairwise_impl = pairwise_impl
    return krum


def make_bulyan(nr_byzantine: int, pairwise_impl: str = "auto"):
    """Bulyan (El Mhamdi et al., ICML 2018, public): Krum-select a
    θ = m - 2f committee, then aggregate it with a per-coordinate trimmed
    mean keeping the θ - 2f values closest to the committee's coordinate
    median.  Combines Krum's distance-based outlier rejection with
    coordinate-wise robustness (a single Krum winner can still carry a few
    poisoned coordinates); needs m >= 4f + 3.

    Selection note: the paper removes the Krum winner and RE-SCORES the
    remaining set θ times; this implementation takes the θ best one-shot
    Krum scores instead — one O(m²d) distance pass, jit-friendly, the common
    deployed simplification — which can admit a different committee than
    iterative re-scoring when a colluding clique reshapes the score
    landscape mid-selection.  The coordinate-wise trimming stage is exact.
    """

    def bulyan(stacked, weights=None, key=None):
        mat, unflatten = _stack_to_matrix(stacked, upcast=False)
        m = mat.shape[0]
        f = nr_byzantine
        theta = m - 2 * f
        beta = theta - 2 * f
        if m < 4 * f + 3:
            raise ValueError(
                f"bulyan needs m >= 4f + 3 (m={m}, f={f})"
            )
        # selection stage: the theta best one-shot Krum scores (see the
        # docstring's selection note vs the paper's iterative variant)
        nr_neighbors = m - f - 2
        sq = _sq_dists(mat, pairwise_impl)
        sq = sq + jnp.diag(jnp.full(m, jnp.inf))
        scores = jnp.sum(jnp.sort(sq, axis=1)[:, :nr_neighbors], axis=1)
        # the committee upcasts to f32 — the coordinate-wise stage sorts
        # and averages it whole, and at (theta, d) that is O(m·d), not the
        # O(m²·d) the distance pass just avoided
        committee = mat[jnp.argsort(scores)[:theta]].astype(jnp.float32)
        # aggregation stage: per-coordinate, keep the beta values nearest
        # the committee median and average them
        med = jnp.median(committee, axis=0)
        dist = jnp.abs(committee - med[None, :])
        nearest = jnp.argsort(dist, axis=0)[:beta]  # (beta, d)
        kept = jnp.take_along_axis(committee, nearest, axis=0)
        return unflatten(jnp.mean(kept, axis=0))

    bulyan.pairwise_impl = pairwise_impl
    return bulyan
