"""Horizontal-FL servers.

Class and constructor shapes mirror the reference's server family
(hfl_complete.py:159-390) — Centralized, FedSGD-gradient, FedAvg — plus the
homework-1 A1 FedSGD-weight variant (lab/homework-1.ipynb cell 12).  The
execution model is inverted, though: instead of a sequential Python loop over
client objects, each round is ONE jitted SPMD program (see fl.engine) in which
all sampled clients step in parallel via vmap and aggregation is a weighted
mean over the client axis.

Round accounting matches the reference exactly:
- message_count is cumulative ``2 * (round+1) * clients_per_round``
  (hfl_complete.py:309,387);
- clients_per_round is ``max(1, round(C * N))`` (hfl_complete.py:228);
- test accuracy is evaluated on the full test set each round
  (hfl_complete.py:172-183).
"""

from __future__ import annotations

from time import perf_counter

import jax
import jax.numpy as jnp

from ..data.split import ClientDatasets
from ..utils.metrics import RunResult
from ..utils.platform import device_sync
from ..utils.rng import seed_key
from .engine import (
    make_fl_round,
    make_full_batch_grad,
    make_local_sgd_update,
    make_lora_local_update,
)
from .task import Task


class Server:
    def __init__(self, task: Task, lr: float, batch_size: int, seed: int):
        self.task = task
        self.lr = lr
        self.batch_size = batch_size
        self.seed = seed
        self.base_key = seed_key(seed)
        init_key, self.run_key = jax.random.split(self.base_key)
        self.params = task.init(init_key)
        self._evaluate = task.evaluator()

    def test(self) -> float:
        return float(self._evaluate(self.params))

    def extra_state(self):
        """Cross-round server state beyond ``params`` that a checkpoint must
        carry for exact resume (e.g. FedOpt's optimizer moments).  The dict
        doubles as the restore template; empty for stateless servers."""
        return {}

    def restore_extra_state(self, state) -> None:
        if state:
            raise ValueError(
                f"{type(self).__name__} has no extra state to restore"
            )


def _make_weight_client_update(task: Task, lr: float, batch_size: int,
                               nr_local_epochs: int,
                               client_data: ClientDatasets,
                               prox_mu: float = 0.0):
    """Shared FedAvg-family construction: validate the padded client layout
    against the batch size and build the E-local-epochs SGD client update."""
    if client_data.max_samples % batch_size != 0:
        raise ValueError(
            "client_data must be stacked with pad_multiple=batch_size "
            f"(max_samples={client_data.max_samples}, batch={batch_size})"
        )
    return make_local_sgd_update(
        task.loss_fn, lr, batch_size, nr_local_epochs, prox_mu=prox_mu
    )


class CentralizedServer(Server):
    """Plain minibatch SGD on the pooled dataset; one round == one epoch
    (reference: hfl_complete.py:193-216)."""

    def __init__(self, task: Task, lr: float, batch_size: int, seed: int,
                 train_x=None, train_y=None):
        super().__init__(task, lr, batch_size, seed)
        n = train_y.shape[0]
        pad_to = -(-n // batch_size) * batch_size
        self._x = jnp.pad(
            jnp.asarray(train_x), [(0, pad_to - n)] + [(0, 0)] * (train_x.ndim - 1)
        )
        self._y = jnp.pad(jnp.asarray(train_y), (0, pad_to - n))
        self._count = jnp.int32(n)
        update = make_local_sgd_update(task.loss_fn, lr, batch_size, 1)
        # dataset as jit arguments, not closure constants (see
        # engine.make_fl_round): keeps the pooled train set out of the HLO
        jitted = jax.jit(update)
        self._epoch = lambda params, key: jitted(
            params, self._x, self._y, self._count, key
        )

    def run(self, nr_rounds: int, start_round: int = 0,
            on_round=None) -> RunResult:
        result = RunResult("Centralized", 1, 1, self.batch_size, 1, self.lr, self.seed)
        elapsed = 0.0
        for r in range(start_round, start_round + nr_rounds):
            t0 = perf_counter()
            epoch_key = jax.random.fold_in(self.run_key, r)
            self.params = device_sync(self._epoch(self.params, epoch_key))
            elapsed += perf_counter() - t0
            result.record_round(elapsed, 0, self.test())
            if on_round is not None:
                on_round(r, result)
        return result


class DecentralizedServer(Server):
    def __init__(self, task: Task, lr: float, batch_size: int,
                 client_data: ClientDatasets, client_fraction: float, seed: int,
                 mesh=None):
        super().__init__(task, lr, batch_size, seed)
        self.client_data = client_data
        self.nr_clients = client_data.nr_clients
        self.client_fraction = client_fraction
        self.mesh = mesh  # shard the sampled-client axis over this mesh
        self.nr_clients_per_round = max(1, round(client_fraction * self.nr_clients))
        self.round_fn = None  # set by subclass
        self.algorithm = "Decentralized"
        self.nr_local_epochs = 1
        # messages each selected client exchanges per round (the reference's
        # 2 = weights down + up, hfl_complete.py:309,387); stateful variants
        # override (SCAFFOLD: +2 control variates)
        self.messages_per_client = 2
        # optional resilience.ValidationGate; run_hfl installs it post-build
        # (it needs the server's evaluator).  None -> rounds install
        # unconditionally, the exact pre-gate behavior.
        self.val_gate = None

    def _advance(self, r: int) -> None:
        """Execute round ``r`` and install its outputs — the ONE hook a
        stateful server overrides (SCAFFOLD threads c/ci through here) so
        every variant shares the timing/accounting loop below."""
        new = device_sync(self.round_fn(self.params, self.run_key, r))
        if self.val_gate is not None:
            new, _ = self.val_gate.admit(r, self.params, new)
        self.params = new

    def run(self, nr_rounds: int, start_round: int = 0,
            on_round=None) -> RunResult:
        """Run rounds ``start_round .. start_round + nr_rounds - 1``.  Round
        keys and message counts derive from the GLOBAL round index, so a
        resumed run (``start_round > 0``) continues the exact key/accounting
        sequence of an uninterrupted one.  ``on_round(global_round, result)``
        fires after each round (streaming metrics / periodic checkpoints)."""
        result = RunResult(
            self.algorithm, self.nr_clients, self.client_fraction,
            self.batch_size, self.nr_local_epochs, self.lr, self.seed,
        )
        elapsed = 0.0
        for r in range(start_round, start_round + nr_rounds):
            t0 = perf_counter()
            self._advance(r)
            elapsed += perf_counter() - t0
            result.record_round(
                elapsed,
                self.messages_per_client * (r + 1) * self.nr_clients_per_round,
                self.test(),
            )
            if on_round is not None:
                on_round(r, result)
        return result


class FedSgdGradientServer(DecentralizedServer):
    """FedSGD: clients return one full-batch gradient; the server applies the
    n_k-weighted average with an SGD step (reference: hfl_complete.py:260-312).
    """

    def __init__(self, task: Task, lr: float, client_data: ClientDatasets,
                 client_fraction: float, seed: int,
                 aggregator=None, attack=None, malicious_mask=None,
                 attack_fraction: float = 0.0, attack_seed: int = 0,
                 mesh=None,
                 compress: str = "none", compress_ratio: float = 0.01,
                 fault_plan=None, round_deadline_s: float | None = None,
                 client_chunk: int = 0, donate: bool = False,
                 robust_stack: str = "float32", secagg=None,
                 secagg_impl: str = "auto",
                 overlap_combine: bool = False, prefetch_depth: int = 0):
        super().__init__(task, lr, -1, client_data, client_fraction, seed,
                         mesh=mesh)
        self.algorithm = "FedSGDGradient"
        client_update = make_full_batch_grad(task.loss_fn)
        self.round_fn = make_fl_round(
            client_update,
            client_data.x, client_data.y, client_data.counts,
            self.nr_clients_per_round,
            aggregator=aggregator,
            apply_aggregate=lambda params, g: jax.tree.map(
                lambda p, gg: p - lr * gg, params, g
            ),
            attack=attack, malicious_mask=malicious_mask,
            attack_fraction=attack_fraction, attack_seed=attack_seed,
            mesh=mesh,
            # gradient server: the client message IS the gradient, so
            # compression acts on it directly, not on a params delta
            compress=compress, compress_ratio=compress_ratio,
            compress_deltas=False,
            fault_plan=fault_plan, round_deadline_s=round_deadline_s,
            client_chunk=client_chunk, donate=donate,
            robust_stack=robust_stack, secagg=secagg,
            secagg_impl=secagg_impl, overlap_combine=overlap_combine,
            prefetch_depth=prefetch_depth,
        )


class FedSgdWeightServer(DecentralizedServer):
    """Homework-1 A1: clients take ONE local full-batch SGD step and return
    *weights*; the server installs their weighted average.  Mathematically
    identical to FedSgdGradientServer round-for-round (the homework shows a
    0.0 accuracy delta; lab/homework-1.ipynb cells 13-18)."""

    def __init__(self, task: Task, lr: float, client_data: ClientDatasets,
                 client_fraction: float, seed: int,
                 aggregator=None, attack=None, malicious_mask=None,
                 attack_fraction: float = 0.0, attack_seed: int = 0,
                 mesh=None,
                 fault_plan=None, round_deadline_s: float | None = None,
                 client_chunk: int = 0, donate: bool = False,
                 robust_stack: str = "float32", secagg=None,
                 secagg_impl: str = "auto",
                 overlap_combine: bool = False, prefetch_depth: int = 0):
        super().__init__(task, lr, -1, client_data, client_fraction, seed,
                         mesh=mesh)
        self.algorithm = "FedSGDWeight"
        client_update = make_local_sgd_update(task.loss_fn, lr, -1, 1)
        self.round_fn = make_fl_round(
            client_update,
            client_data.x, client_data.y, client_data.counts,
            self.nr_clients_per_round,
            aggregator=aggregator,
            attack=attack, malicious_mask=malicious_mask,
            attack_fraction=attack_fraction, attack_seed=attack_seed,
            mesh=mesh,
            fault_plan=fault_plan, round_deadline_s=round_deadline_s,
            client_chunk=client_chunk, donate=donate,
            robust_stack=robust_stack, secagg=secagg,
            secagg_impl=secagg_impl, overlap_combine=overlap_combine,
            prefetch_depth=prefetch_depth,
        )


class FedAvgServer(DecentralizedServer):
    """FedAvg: clients run E local epochs of minibatch SGD and return weights;
    the server installs the n_k-weighted average
    (reference: hfl_complete.py:336-390).

    Extensions beyond the reference:
    - ``prox_mu > 0`` turns local training into FedProx (proximal term
      against the round-start weights; Li et al., MLSys 2020);
    - ``dropout_rate > 0`` simulates per-round client failures with
      survivor renormalisation (see fl.engine.make_fl_round).
    """

    def __init__(self, task: Task, lr: float, batch_size: int,
                 client_data: ClientDatasets, client_fraction: float,
                 nr_local_epochs: int, seed: int,
                 aggregator=None, attack=None, malicious_mask=None,
                 attack_fraction: float = 0.0, attack_seed: int = 0,
                 mesh=None,
                 prox_mu: float = 0.0, dropout_rate: float = 0.0,
                 dp_clip: float = 0.0, dp_noise_mult: float = 0.0,
                 compress: str = "none", compress_ratio: float = 0.01,
                 fault_plan=None, round_deadline_s: float | None = None,
                 client_chunk: int = 0, donate: bool = False,
                 robust_stack: str = "float32", secagg=None,
                 secagg_impl: str = "auto",
                 overlap_combine: bool = False, prefetch_depth: int = 0):
        super().__init__(task, lr, batch_size, client_data, client_fraction,
                         seed, mesh=mesh)
        self.algorithm = "FedAvg" if prox_mu == 0.0 else "FedProx"
        if dp_clip:
            self.algorithm = "DP-" + self.algorithm
        self.nr_local_epochs = nr_local_epochs
        client_update = _make_weight_client_update(
            task, lr, batch_size, nr_local_epochs, client_data, prox_mu
        )
        self.round_fn = make_fl_round(
            client_update,
            client_data.x, client_data.y, client_data.counts,
            self.nr_clients_per_round,
            aggregator=aggregator,
            attack=attack, malicious_mask=malicious_mask,
            attack_fraction=attack_fraction, attack_seed=attack_seed,
            mesh=mesh, dropout_rate=dropout_rate,
            dp_clip=dp_clip, dp_noise_mult=dp_noise_mult,
            # weight server: the client message is its params delta
            compress=compress, compress_ratio=compress_ratio,
            compress_deltas=True,
            fault_plan=fault_plan, round_deadline_s=round_deadline_s,
            client_chunk=client_chunk, donate=donate,
            robust_stack=robust_stack, secagg=secagg,
            secagg_impl=secagg_impl, overlap_combine=overlap_combine,
            prefetch_depth=prefetch_depth,
        )


class FedLoRAAvgServer(DecentralizedServer):
    """Federated LoRA: FedAvg's exact round machinery, but the params
    tree the round carries is ONLY the adapter subtree.

    ``task.init`` must return a LoRA-config tree (``lora_rank > 0`` —
    e.g. ``Llama`` with ``lora_rank=8``); the ctor freezes it as the
    base and replaces ``self.params`` with ``slice_adapter`` of it, so
    client sampling, secure aggregation (over the flattened low-rank
    factors), DP clip/noise, dropout renormalisation, and delta
    compression all run over the adapter with zero engine changes.
    Zero-init ``lora_B`` makes round 0's adapter a bitwise no-op on the
    model, matching serving's reserved null adapter.

    The promoted adapter is the per-tenant serving artifact: feed
    ``self.params`` (``slice_adapter`` wire format) straight to
    ``serving_fleet.tenants.TenantAdapterPlane.push_tenant_round``.
    ``test()`` evaluates the FULL model (base + live adapter).
    """

    def __init__(self, task: Task, lr: float, batch_size: int,
                 client_data: ClientDatasets, client_fraction: float,
                 nr_local_epochs: int, seed: int,
                 aggregator=None, mesh=None, dropout_rate: float = 0.0,
                 dp_clip: float = 0.0, dp_noise_mult: float = 0.0,
                 compress: str = "none", compress_ratio: float = 0.01,
                 secagg=None, secagg_impl: str = "auto"):
        super().__init__(task, lr, batch_size, client_data, client_fraction,
                         seed, mesh=mesh)
        self.algorithm = "FedLoRA"
        if dp_clip:
            self.algorithm = "DP-" + self.algorithm
        self.nr_local_epochs = nr_local_epochs
        if client_data.max_samples % batch_size != 0:
            raise ValueError(
                "client_data must be stacked with pad_multiple=batch_size "
                f"(max_samples={client_data.max_samples}, "
                f"batch={batch_size})"
            )
        from ..models.lora import apply_adapter, slice_adapter

        self._apply_adapter = apply_adapter
        self.base_params = self.params      # frozen LoRA-config tree
        self.params = slice_adapter(self.params)
        client_update = make_lora_local_update(
            task.loss_fn, self.base_params, lr, batch_size,
            nr_local_epochs,
        )
        self.round_fn = make_fl_round(
            client_update,
            client_data.x, client_data.y, client_data.counts,
            self.nr_clients_per_round,
            aggregator=aggregator,
            mesh=mesh, dropout_rate=dropout_rate,
            dp_clip=dp_clip, dp_noise_mult=dp_noise_mult,
            # adapter server: the client message is its factor delta
            compress=compress, compress_ratio=compress_ratio,
            compress_deltas=True,
            secagg=secagg, secagg_impl=secagg_impl,
        )

    def full_params(self):
        """Base tree with the live federated factors grafted in — what
        the serving side merges/installs."""
        return self._apply_adapter(self.base_params, self.params)

    def test(self) -> float:
        return float(self._evaluate(self.full_params()))


class FedOptServer(DecentralizedServer):
    """FedOpt (Reddi et al., 2021): the round's n_k-weighted client average
    is turned into a pseudo-gradient Δ = w_server − w_avg and fed to a
    server-side optax optimizer — FedAvgM (SGD+momentum), FedAdam, FedYogi.
    New capability beyond the reference, which only ever overwrites server
    params with the average (hfl_complete.py:380-383); ``sgd`` with
    ``server_lr=1.0`` reproduces exactly that.

    The client phase is the same one jitted SPMD program as FedAvg; the
    server step is a second tiny jit whose optimizer state lives on device
    between rounds.
    """

    OPTIMIZERS = ("sgd", "avgm", "adam", "yogi")

    def __init__(self, task: Task, lr: float, batch_size: int,
                 client_data: ClientDatasets, client_fraction: float,
                 nr_local_epochs: int, seed: int,
                 server_optimizer: str = "adam", server_lr: float = 1e-2,
                 aggregator=None, attack=None, malicious_mask=None,
                 attack_fraction: float = 0.0, attack_seed: int = 0,
                 mesh=None, zero_server: bool = False,
                 prox_mu: float = 0.0, dropout_rate: float = 0.0,
                 fault_plan=None, round_deadline_s: float | None = None,
                 client_chunk: int = 0, robust_stack: str = "float32",
                 secagg=None, secagg_impl: str = "auto",
                 overlap_combine: bool = False, prefetch_depth: int = 0):
        super().__init__(task, lr, batch_size, client_data, client_fraction,
                         seed, mesh=mesh)
        if server_optimizer not in self.OPTIMIZERS:
            raise ValueError(
                f"server_optimizer={server_optimizer!r} not in "
                f"{self.OPTIMIZERS}"
            )
        import optax

        self.algorithm = f"FedOpt-{server_optimizer}"
        self.nr_local_epochs = nr_local_epochs
        # eps here is the FedOpt paper's tau (adaptivity floor); the Adam
        # default 1e-8 turns every coordinate update into +-server_lr, which
        # destroys convergence at FL's round counts
        opt = {
            "sgd": lambda: optax.sgd(server_lr),
            "avgm": lambda: optax.sgd(server_lr, momentum=0.9),
            "adam": lambda: optax.adam(server_lr, eps=1e-3),
            "yogi": lambda: optax.yogi(server_lr, eps=1e-3),
        }[server_optimizer]()
        if zero_server and mesh is None:
            raise ValueError(
                "zero_server=True needs a clients mesh to shard the server "
                "optimizer state over (set mesh_clients)"
            )
        self.zero_server = zero_server
        if not zero_server:
            self._opt_state = opt.init(self.params)

        client_update = _make_weight_client_update(
            task, lr, batch_size, nr_local_epochs, client_data, prox_mu
        )
        aggregate_fn = make_fl_round(
            client_update,
            client_data.x, client_data.y, client_data.counts,
            self.nr_clients_per_round,
            aggregator=aggregator,
            apply_aggregate=lambda params, agg: agg,  # return w_avg itself
            attack=attack, malicious_mask=malicious_mask,
            attack_fraction=attack_fraction, attack_seed=attack_seed,
            mesh=mesh, dropout_rate=dropout_rate,
            fault_plan=fault_plan, round_deadline_s=round_deadline_s,
            # no donate here: round_fn below reuses params after the
            # aggregate (server_step takes the same buffer) — donating it
            # would hand XLA a buffer the next line still reads
            client_chunk=client_chunk, robust_stack=robust_stack,
            secagg=secagg, secagg_impl=secagg_impl,
            overlap_combine=overlap_combine, prefetch_depth=prefetch_depth,
        )

        if zero_server:
            # ZeRO-1 server update: moments and update live on a 1/W slice
            # per replica of the clients mesh (parallel.zero); the scatter+
            # gather pair is accounted like the round's own psums
            from ..parallel.collectives import instrument_collectives
            from ..parallel.zero import make_zero_server_step

            server_step, self._opt_state = make_zero_server_step(
                opt, mesh, self.params, axis="clients"
            )
            nbytes = 4 * sum(
                l.size for l in jax.tree.leaves(self.params)
            )
            server_step = instrument_collectives(
                server_step,
                lambda *a, **k: [
                    ("psum_scatter", 1, nbytes),
                    ("all_gather", 1, nbytes),
                ],
                op="fl.server_zero",
            )
            from .. import obs

            # per-replica server-optimizer bytes: the sharded state's array
            # leaves carry a leading (W,) shard axis, so one replica holds
            # leaf.size / W elements of each
            W = mesh.shape["clients"]
            opt_bytes = sum(
                (l.size // W) * l.dtype.itemsize
                for l in jax.tree.leaves(self._opt_state)
                if hasattr(l, "size") and l.ndim
            )
            if obs.enabled():
                obs.set_gauge("fl_server_opt_bytes_per_replica", opt_bytes)
                obs.set_gauge("fl_zero_server_world", W)
        else:
            @jax.jit
            def server_step(params, opt_state, w_avg):
                delta = jax.tree.map(jnp.subtract, params, w_avg)
                updates, opt_state = opt.update(delta, opt_state, params)
                return optax.apply_updates(params, updates), opt_state

        def round_fn(params, base_key, round_idx):
            w_avg = aggregate_fn(params, base_key, round_idx)
            params, self._opt_state = server_step(
                params, self._opt_state, w_avg
            )
            return params

        # surface the inner round's secagg session + oracle so tests and
        # run_hfl reporting see FedOpt like the direct servers
        round_fn.secagg = getattr(aggregate_fn, "secagg", None)
        round_fn.secagg_oracle = getattr(aggregate_fn, "secagg_oracle", None)
        round_fn.secagg_fused = getattr(aggregate_fn, "secagg_fused", False)
        round_fn.cohort_shard = getattr(aggregate_fn, "cohort_shard", 1)
        round_fn.server_step = server_step  # tests drive the zero step raw
        self._server_step = server_step
        self.round_fn = round_fn

    def extra_state(self):
        return {"server_opt_state": self._opt_state}

    def restore_extra_state(self, state) -> None:
        self._opt_state = state["server_opt_state"]
