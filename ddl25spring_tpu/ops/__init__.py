from .losses import (
    nll_loss,
    cross_entropy_logits,
    causal_lm_loss,
    accuracy,
)

__all__ = [
    "nll_loss",
    "cross_entropy_logits",
    "causal_lm_loss",
    "accuracy",
]
