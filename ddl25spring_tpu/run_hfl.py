"""CLI runner for horizontal-FL experiments.

    python -m ddl25spring_tpu.run_hfl --algorithm fedavg --nr-clients 10 \
        --client-fraction 0.1 --nr-rounds 10

reproduces the homework-1 experiment grid (lab/homework-1.ipynb cell 22) and
prints the RunResult table; Byzantine attack/defense configs (the missing
course part 3, SURVEY.md §2.2) plug in via --aggregator/--attack flags.

Beyond the reference: ``--algorithm fedprox --prox-mu 0.1`` (proximal local
SGD), ``--algorithm fedopt --server-optimizer adam|yogi|avgm`` (adaptive
server optimizers over the round delta), ``--algorithm scaffold``
(control-variate drift correction, fl/scaffold.py), and ``--dropout-rate``
(per-round client failure simulation with survivor renormalisation).

``--secagg`` runs the linear servers (fedsgd/fedsgd-weight/fedavg/fedprox/
fedopt/fedbuff) over masked fixed-point sums (ddl25spring_tpu.secagg): the
server only ever sees the cohort's modular sum, dropped clients are
excluded via Shamir mask recovery (combine with --fault-spec drop=...),
and --secagg-clip/--secagg-threshold size the field's overflow budget and
the recovery threshold.  ``--secagg-groups G`` (G > 1) splits each round's
cohort into G masked sessions so the server decodes G group aggregates —
the ONLY configuration where --secagg composes with a robust --aggregator
(the rule then reduces over group sums instead of per-client updates;
privacy granularity drops accordingly).  ``--attack-fraction`` draws a
fresh seeded Byzantine coalition each round, and ``--val-gate
skip|clip|restore`` re-scores every round's aggregate on the holdout set
before installing it.  Threat model and caveats: docs/SECURITY.md.
"""

from __future__ import annotations

import numpy as np

from . import obs
from .configs import HflConfig, parse_config
from .data import load_cifar10, load_mnist, split_dataset
from .fl import (
    CentralizedServer,
    FedAvgServer,
    FedOptServer,
    FedSgdGradientServer,
    FedSgdWeightServer,
)
from .fl.task import classification_task
from .models import MnistCnn, ResNet18
from .robust import (
    coordinate_median,
    make_bulyan,
    make_consensus,
    flip_labels,
    make_gaussian_attack,
    make_krum,
    make_sign_flip_attack,
    make_trimmed_mean,
)
from .utils import Checkpointer, MetricsLogger


def build_attack(cfg: HflConfig):
    """Update-attack factory for ``--attack``.

    ``label-flip`` is a DATA attack (poisons the stacked datasets before
    training) and ``none`` is no attack — both return None here; the
    update attacks return the callable ``make_fl_round`` dispatches on
    (collusive ones, like ALIE, carry ``.collusive`` for the engine's
    whole-stack hook)."""
    if cfg.attack == "gaussian":
        return make_gaussian_attack()
    if cfg.attack == "sign-flip":
        return make_sign_flip_attack()
    if cfg.attack == "alie":
        from .robust import make_alie_attack

        return make_alie_attack()
    if cfg.attack in ("none", "label-flip"):
        return None
    raise ValueError(f"unknown attack {cfg.attack!r}")


def build_aggregator(cfg: HflConfig):
    sampled = max(1, round(cfg.client_fraction * cfg.nr_clients))
    if cfg.aggregator == "mean":
        return None
    if cfg.aggregator == "median":
        return coordinate_median
    if cfg.aggregator == "consensus":
        if cfg.algorithm not in ("fedsgd",):
            raise ValueError(
                "consensus aggregation needs gradient-type updates; use "
                "--algorithm fedsgd"
            )
        return make_consensus()
    if cfg.aggregator == "trimmed-mean":
        return make_trimmed_mean(min(0.45, max(1, cfg.nr_malicious) / sampled))
    if cfg.aggregator == "krum":
        return make_krum(cfg.nr_malicious, 1,
                         pairwise_impl=cfg.pairwise_impl)
    if cfg.aggregator == "multi-krum":
        return make_krum(cfg.nr_malicious,
                         max(1, sampled - 2 * cfg.nr_malicious),
                         pairwise_impl=cfg.pairwise_impl)
    if cfg.aggregator == "bulyan":
        return make_bulyan(cfg.nr_malicious,
                           pairwise_impl=cfg.pairwise_impl)
    raise ValueError(f"unknown aggregator {cfg.aggregator!r}")


def build_secagg(cfg: HflConfig, client_data):
    """Per-run secure-aggregation session (None when --secagg is off).

    Under --dp-clip the aggregation weights are uniform (n_k weighting
    would leak client data sizes), so the overflow budget is sized for
    cohort_size; otherwise it is sized against the cohort_size largest
    client counts — see secagg/field.py for the formula."""
    if not cfg.secagg:
        return None
    from .secagg.protocol import SecAgg

    clients_per_round = max(1, round(cfg.client_fraction * cfg.nr_clients))
    counts = None if cfg.dp_clip else np.asarray(client_data.counts)
    return SecAgg(cfg.nr_clients, clients_per_round, counts=counts,
                  clip=cfg.secagg_clip,
                  threshold_frac=cfg.secagg_threshold, seed=cfg.seed,
                  nr_groups=cfg.secagg_groups)


def build_clients_mesh(spec: str, clients_per_round: int):
    """Resolve ``HflConfig.mesh_clients`` into the cohort-sharding mesh.

    ``"0"`` — no mesh, the exact single-device program.  ``"auto"`` — the
    historical heuristic: all local devices, but only when more than one
    exists and the sampled cohort is at least that large (below that,
    shard padding wastes compute).  ``"N"`` — exactly N devices, failing
    LOUDLY when unavailable instead of silently degrading — the point of
    making the choice explicit config.  Under multi-controller JAX the
    clients axis subdivides each host's local devices and an outer ``dcn``
    axis spans hosts (parallel/multihost.py).
    """
    import jax

    from .parallel import make_mesh, make_multihost_mesh

    nr_devices = len(jax.devices())
    if spec == "auto":
        nr = nr_devices
        if nr <= 1 or clients_per_round < nr:
            return None
    else:
        nr = int(spec)
        if nr == 0:
            return None
        if nr > nr_devices:
            raise ValueError(
                f"mesh_clients={nr} but only {nr_devices} device(s) "
                f"available"
            )
    if jax.process_count() > 1:
        local = nr // jax.process_count()
        if local * jax.process_count() != nr:
            raise ValueError(
                f"mesh_clients={nr} does not split evenly over "
                f"{jax.process_count()} processes"
            )
        return make_multihost_mesh(ici_axes={"clients": local})
    return make_mesh({"clients": nr}, devices=jax.devices()[:nr])


def build_server(cfg: HflConfig):
    from .resilience.faults import FaultPlan

    fault_plan = FaultPlan.parse(cfg.fault_spec)
    round_deadline_s = cfg.round_deadline_s or None
    if fault_plan is not None and cfg.algorithm in ("centralized", "scaffold"):
        raise ValueError(
            f"--fault-spec is not wired into {cfg.algorithm!r} "
            "(centralized has no clients to fail; scaffold's "
            "control-variate update assumes honest full participation)"
        )
    if ((cfg.dp_clip or cfg.dp_noise_mult)
            and cfg.algorithm not in ("fedavg", "fedprox")):
        raise ValueError(
            "--dp-clip/--dp-noise-mult are implemented for fedavg/fedprox "
            f"only; algorithm {cfg.algorithm!r} would silently train "
            "without privacy"
        )
    if (cfg.compress != "none"
            and cfg.algorithm not in ("fedsgd", "fedavg", "fedprox")):
        raise ValueError(
            "--compress is implemented for fedsgd/fedavg/fedprox only; "
            f"algorithm {cfg.algorithm!r} would silently train with "
            "uncompressed uplinks"
        )
    if cfg.attack_fraction and cfg.attack in ("none", "label-flip"):
        raise ValueError(
            "--attack-fraction draws per-round UPDATE attackers and needs "
            f"an update attack to apply (--attack {cfg.attack!r} "
            "is not one); pass --attack gaussian|sign-flip|alie"
        )
    if cfg.secagg_groups > 1 and not cfg.secagg:
        raise ValueError(
            "--secagg-groups > 1 configures group-wise MASKED sessions and "
            "needs --secagg true"
        )
    if cfg.val_gate and cfg.algorithm in ("centralized", "scaffold"):
        raise ValueError(
            f"--val-gate is not wired into {cfg.algorithm!r} (it hooks the "
            "decentralized round-install boundary, which centralized lacks "
            "and scaffold overrides for its control-variate state)"
        )
    if cfg.secagg:
        # reject every incompatible combination BEFORE the dataset loads;
        # docs/SECURITY.md explains each one
        if cfg.algorithm in ("centralized", "scaffold"):
            raise ValueError(
                f"--secagg is not wired into {cfg.algorithm!r} "
                "(centralized has no client uplinks to mask; scaffold's "
                "control variates are a second per-client message the "
                "masked-sum protocol does not cover)"
            )
        if cfg.aggregator != "mean" and cfg.secagg_groups <= 1:
            raise ValueError(
                "--secagg cannot combine with a robust aggregator "
                f"({cfg.aggregator!r}) at --secagg-groups 1: robust rules "
                "need more than the single cohort sum the server decodes. "
                "Pass --secagg-groups G > 1 to decode one masked sum per "
                "group and robust-reduce over the G group aggregates "
                "(granularity-vs-robustness tradeoff: docs/SECURITY.md)"
            )
        if cfg.aggregator != "mean" and cfg.algorithm == "fedbuff":
            raise ValueError(
                "fedbuff has no robust-aggregator hook (its grouped secagg "
                "mode recombines group sums with the staleness-weighted "
                "mean); drop --aggregator or use a synchronous server"
            )
        if cfg.dropout_rate:
            raise ValueError(
                "--secagg does not combine with --dropout-rate; simulate "
                "client failures with --fault-spec drop=... instead, where "
                "dropped clients are excluded via Shamir mask recovery"
            )
        if cfg.compress != "none":
            raise ValueError(
                "--secagg replaces uplink compression: the fixed-point "
                "field encoding IS the quantized uplink (--compress "
                f"{cfg.compress!r} would double-quantize the messages)"
            )
    # datasets ship as raw uint8 and are normalized on device inside the
    # jitted loss/score fns — 4x less host->device transfer, which matters
    # on the remote-tunnel TPU (data/mnist.py raw_dataset)
    if cfg.dataset == "mnist":
        from .data.mnist import mnist_input_transform

        ds = load_mnist(raw=True)
        task = classification_task(MnistCnn(), (28, 28, 1), ds.test_x,
                                   ds.test_y,
                                   input_transform=mnist_input_transform())
    elif cfg.dataset == "cifar10":
        from .data.cifar import cifar_input_transform

        ds = load_cifar10(raw=True)
        task = classification_task(ResNet18(), (32, 32, 3), ds.test_x,
                                   ds.test_y,
                                   input_transform=cifar_input_transform())
    else:
        raise ValueError(f"unknown dataset {cfg.dataset!r}")

    if cfg.algorithm == "centralized":
        return CentralizedServer(task, cfg.lr, cfg.batch_size, cfg.seed,
                                 train_x=ds.train_x, train_y=ds.train_y)

    if cfg.algorithm == "fedbuff":
        # async server: robust aggregators reduce whole update stacks and
        # have no hook here; attacks DO apply (they poison the outgoing
        # delta, the async message)
        if cfg.aggregator != "mean" or cfg.dropout_rate:
            raise ValueError(
                "fedbuff does not combine with robust aggregators or "
                "dropout_rate (async staleness already models lag; "
                "failure simulation rides --fault-spec)"
            )
        from .fl import FedBuffServer

        client_data = split_dataset(ds.train_x, ds.train_y, cfg.nr_clients,
                                    cfg.iid, cfg.seed,
                                    pad_multiple=cfg.batch_size)
        malicious = np.zeros(cfg.nr_clients, dtype=bool)
        if cfg.nr_malicious:
            malicious[np.random.default_rng(cfg.seed).choice(
                cfg.nr_clients, cfg.nr_malicious, replace=False)] = True
        attack = build_attack(cfg)
        if cfg.attack == "label-flip":
            client_data = flip_labels(client_data, malicious, nr_classes=10)
        buff_cohort = max(1, round(cfg.client_fraction * cfg.nr_clients))
        return FedBuffServer(
            task, cfg.lr, cfg.batch_size, client_data, cfg.client_fraction,
            cfg.nr_local_epochs, cfg.seed,
            staleness_window=cfg.staleness_window,
            staleness_exp=cfg.staleness_exp, server_eta=cfg.server_eta,
            mesh=build_clients_mesh(cfg.mesh_clients, buff_cohort),
            attack=attack,
            malicious_mask=malicious if attack is not None else None,
            attack_fraction=cfg.attack_fraction, attack_seed=cfg.attack_seed,
            fault_plan=fault_plan, round_deadline_s=round_deadline_s,
            client_chunk=cfg.client_chunk,
            # same donation predicate as the sync servers below: the tick
            # donates its history carry only when no async checkpointer or
            # validation gate holds a reference to it past the dispatch
            donate=(cfg.client_chunk > 0 and not cfg.val_gate
                    and not (cfg.checkpoint_dir and cfg.checkpoint_every)),
            secagg=build_secagg(cfg, client_data),
            secagg_impl=cfg.secagg_impl,
            # fedbuff ticks are async and already host-feed per tick, so
            # prefetch_depth does not apply; the overlapped combine does
            overlap_combine=cfg.overlap_combine,
        )

    if cfg.algorithm == "scaffold":
        if cfg.aggregator != "mean" or cfg.attack != "none" or cfg.dropout_rate:
            raise ValueError(
                "scaffold does not combine with robust aggregators, attacks, "
                "or dropout_rate (the control-variate update assumes honest "
                "full participation of the sampled set)"
            )
        from .fl import ScaffoldServer

        client_data = split_dataset(ds.train_x, ds.train_y, cfg.nr_clients,
                                    cfg.iid, cfg.seed,
                                    pad_multiple=cfg.batch_size)
        return ScaffoldServer(
            task, cfg.lr, cfg.batch_size, client_data, cfg.client_fraction,
            cfg.nr_local_epochs, cfg.seed,
            server_lr=cfg.scaffold_server_lr,
            client_chunk=cfg.client_chunk,
        )

    pad = cfg.batch_size if cfg.algorithm in ("fedavg", "fedprox", "fedopt") else 1
    client_data = split_dataset(ds.train_x, ds.train_y, cfg.nr_clients,
                                cfg.iid, cfg.seed, pad_multiple=pad)

    malicious = np.zeros(cfg.nr_clients, dtype=bool)
    if cfg.nr_malicious:
        malicious[np.random.default_rng(cfg.seed).choice(
            cfg.nr_clients, cfg.nr_malicious, replace=False)] = True

    attack = build_attack(cfg)
    if cfg.attack == "label-flip":  # data attack: poisons the datasets
        client_data = flip_labels(client_data, malicious, nr_classes=10)

    clients_per_round = max(1, round(cfg.client_fraction * cfg.nr_clients))
    # cohort-sharding mesh from EXPLICIT config (mesh_clients), not a
    # silent device-count heuristic — "auto" reproduces the old behaviour
    mesh = build_clients_mesh(cfg.mesh_clients, clients_per_round)
    # donate params on the chunked round when no async checkpointer can
    # hold a live reference to server.params across the next dispatch (the
    # on_round save serializes the buffer donation would let XLA overwrite)
    # — the server reassignment pattern is then safe, the chunked round's
    # scan carry aliases in place, and engine.donation_safe still retracts
    # the donation whenever the persistent compilation cache is on (the
    # jax-0.4.37 deserialized-executable ordering bug its docstring
    # documents).  FedOpt stays off: its round_fn reuses the params it
    # passed (server_step reads the same buffer after the aggregate).  A
    # validation gate also blocks donation — _advance hands the gate the
    # ROUND-INPUT params for the rollback comparison after the round ran.
    donate = (cfg.client_chunk > 0 and not cfg.val_gate
              and not (cfg.checkpoint_dir and cfg.checkpoint_every))
    kw = dict(aggregator=build_aggregator(cfg), attack=attack,
              malicious_mask=malicious if attack is not None else None,
              attack_fraction=cfg.attack_fraction,
              attack_seed=cfg.attack_seed,
              mesh=mesh, fault_plan=fault_plan,
              round_deadline_s=round_deadline_s,
              client_chunk=cfg.client_chunk, robust_stack=cfg.robust_stack,
              secagg=build_secagg(cfg, client_data),
              secagg_impl=cfg.secagg_impl,
              overlap_combine=cfg.overlap_combine,
              prefetch_depth=cfg.prefetch_depth)
    if cfg.algorithm == "fedsgd":
        return FedSgdGradientServer(task, cfg.lr, client_data,
                                    cfg.client_fraction, cfg.seed,
                                    compress=cfg.compress,
                                    compress_ratio=cfg.compress_ratio,
                                    donate=donate, **kw)
    if cfg.algorithm == "fedsgd-weight":
        return FedSgdWeightServer(task, cfg.lr, client_data,
                                  cfg.client_fraction, cfg.seed,
                                  donate=donate, **kw)
    if cfg.algorithm in ("fedavg", "fedprox"):
        prox_mu = cfg.prox_mu if cfg.algorithm == "fedprox" else 0.0
        if cfg.algorithm == "fedprox" and prox_mu <= 0:
            raise ValueError("fedprox needs --prox-mu > 0")
        return FedAvgServer(task, cfg.lr, cfg.batch_size, client_data,
                            cfg.client_fraction, cfg.nr_local_epochs,
                            cfg.seed, prox_mu=prox_mu,
                            dropout_rate=cfg.dropout_rate,
                            dp_clip=cfg.dp_clip,
                            dp_noise_mult=cfg.dp_noise_mult,
                            compress=cfg.compress,
                            compress_ratio=cfg.compress_ratio,
                            donate=donate, **kw)
    if cfg.algorithm == "fedopt":
        if cfg.zero_server and mesh is None:
            raise ValueError(
                "--zero-server needs the clients mesh to resolve "
                "(mesh_clients='auto' found no usable devices; pass "
                "--mesh-clients N explicitly)"
            )
        return FedOptServer(task, cfg.lr, cfg.batch_size, client_data,
                            cfg.client_fraction, cfg.nr_local_epochs,
                            cfg.seed, server_optimizer=cfg.server_optimizer,
                            server_lr=cfg.server_lr, prox_mu=cfg.prox_mu,
                            dropout_rate=cfg.dropout_rate,
                            zero_server=cfg.zero_server, **kw)
    raise ValueError(f"unknown algorithm {cfg.algorithm!r}")


def run(cfg: HflConfig):
    if cfg.telemetry:
        from .obs import watchdog as obs_watchdog

        obs.enable(cfg.telemetry)
        obs.trace.ensure()  # adopt DDL25_TRACEPARENT or start a new trace
        obs_watchdog.install()
    server = build_server(cfg)
    shard = getattr(server.round_fn, "cohort_shard", 1) or 1
    if shard > 1 or getattr(server, "zero_server", False):
        chunk = getattr(server.round_fn, "client_chunk", None)
        cohort = getattr(server.round_fn, "nr_sampled",
                         server.nr_clients_per_round)
        print(f"[mesh] clients axis = {shard} replicas; "
              f"cohort {cohort} -> {cohort // shard} clients/replica"
              + (f", streamed in chunks of {chunk // shard}" if chunk
                 else "")
              + ("; zero-server: optimizer state sharded "
                 f"1/{shard} per replica"
                 if getattr(server, "zero_server", False) else "")
              + ("; overlapped ring combine"
                 if getattr(server.round_fn, "overlap", False) else ""))
    if getattr(server.round_fn, "prefetch_depth", 0):
        print(f"[feed] host-feed pipeline: prefetch_depth="
              f"{server.round_fn.prefetch_depth} (round r+1 device_put "
              "overlaps round r compute)")
    if cfg.val_gate:
        from .resilience import ValidationGate

        # the gate re-scores each round's candidate params with the
        # server's own holdout evaluator (for FedBuff that wrapper already
        # evaluates the newest history slot)
        server.val_gate = ValidationGate(
            server._evaluate, policy=cfg.val_gate,
            tolerance=cfg.val_gate_tolerance,
        )
    logger = MetricsLogger(cfg.metrics_path) if cfg.metrics_path else None
    ckpt = (Checkpointer(cfg.checkpoint_dir)
            if cfg.checkpoint_dir and cfg.checkpoint_every else None)

    start_round = 0
    if ckpt is not None and ckpt.latest_step() is not None:
        # "extra" (server optimizer state etc.) joins the template only when
        # the server has some, so stateless servers keep reading checkpoints
        # written before the field existed
        template = {"params": server.params, "round": 0}
        extra = server.extra_state()
        if extra:
            template["extra"] = extra
        restored = ckpt.restore(template)
        server.params = restored["params"]
        if extra:
            server.restore_extra_state(restored["extra"])
        start_round = int(restored["round"])

    def on_round(r, result):
        # stream metrics and checkpoint as rounds complete, so a crashed run
        # resumes from the last saved round instead of restarting at zero
        if logger is not None:
            logger.log("round", idx=r + 1,
                       wall_time=result.wall_time[-1],
                       message_count=result.message_count[-1],
                       test_accuracy=result.test_accuracy[-1])
        if ckpt is not None and (r + 1) % cfg.checkpoint_every == 0:
            payload = {"params": server.params, "round": r + 1}
            extra = server.extra_state()
            if extra:
                payload["extra"] = extra
            # async: the write overlaps the next round; close() drains it
            ckpt.save(r + 1, payload, wait=False)

    nr_remaining = max(0, cfg.nr_rounds - start_round)
    try:
        with obs.span("hfl.run", algorithm=cfg.algorithm,
                      rounds=nr_remaining):
            result = server.run(nr_remaining, start_round=start_round,
                                on_round=on_round)
    finally:
        # saves are async (on_round): drain + close even on a mid-run crash,
        # or the newest checkpoint dies uncommitted with the process — the
        # exact durability the per-round save exists to provide
        if ckpt is not None:
            ckpt.close()
            ckpt = None

    if cfg.dp_noise_mult:
        from .fl.privacy import dp_epsilon

        # the EFFECTIVE sampling rate, not the nominal fraction: rounding
        # can raise q (N=10, C=0.05 samples 1 client — q=0.1, 2x nominal),
        # which would understate the printed ε.  Read the LIVE value off the
        # server so the report can never drift from what the mechanism did.
        q = server.nr_clients_per_round / cfg.nr_clients
        eps = dp_epsilon(cfg.dp_noise_mult, q, cfg.nr_rounds, cfg.dp_delta)
        secagg_note = (
            "; composition ordering: clip -> fixed-point encode -> mask -> "
            "masked sum -> decode -> server-side Gaussian noise, i.e. DP "
            "noise is added AFTER secure aggregation on the decoded "
            "aggregate (docs/SECURITY.md)"
            if cfg.secagg else ""
        )
        print(f"[dp] client-level privacy spent: ε = {eps:.3f} at "
              f"δ = {cfg.dp_delta:g} (σ = {cfg.dp_noise_mult}, "
              f"q = {q:.4g}, {cfg.nr_rounds} rounds; "
              f"RDP accountant, fl/privacy.py — Poisson-subsampling "
              f"approximation: the engine samples a FIXED-SIZE subset, so "
              f"ε can be optimistic under replace-one adjacency"
              f"{secagg_note})")

    secagg = getattr(server.round_fn, "secagg", None)
    if secagg is not None:
        s = secagg.stats
        print(f"[secagg] {secagg.describe()}; rounds={s['rounds']} "
              f"faulty={s['faulty_rounds']} "
              f"recovered pair_keys={s['recovered_pair_keys']} "
              f"self_seeds={s['recovered_self_seeds']} "
              f"unmask_failures={s['unmask_failures']} "
              f"(simulated key agreement — see docs/SECURITY.md)")

    gate = getattr(server, "val_gate", None)
    if gate is not None:
        best = "n/a" if gate.best_score is None else f"{gate.best_score:.2f}"
        print(f"[val-gate] policy={gate.policy} "
              f"tolerance={gate.tolerance:g} rejections={gate.events} "
              f"best_holdout={best}")

    if logger is not None:
        logger.close()
    obs.flush()  # one telemetry_summary event; no-op when disabled
    if cfg.plot_dir and result.test_accuracy:
        from pathlib import Path

        from .utils import plot_accuracy_curves

        label = f"{result.algorithm} N={cfg.nr_clients} C={cfg.client_fraction}"
        out = plot_accuracy_curves(
            {label: result},
            Path(cfg.plot_dir) / f"hfl_{cfg.algorithm}_accuracy.png",
            title="Test accuracy per round "
                  "(horizontal-federated-learning.ipynb cell 37)",
        )
        print(f"wrote {out}")
    return result


def main(argv=None):
    from .utils.platform import select_platform

    select_platform()
    cfg = parse_config(HflConfig, argv)
    result = run(cfg)
    print(result.as_df().to_string(index=False))
    return result


if __name__ == "__main__":
    main()
