"""Device-mesh construction.

Replaces the reference's L1+L2 layers wholesale (SURVEY.md §1): instead of N
OS processes rendezvousing over gloo TCP (``init_process_group("gloo", rank,
world_size)``, intro_DP_GA.py:12-15), parallelism is expressed as named axes
of one ``jax.sharding.Mesh`` and programs are single SPMD jits.  The
reference's process groups (``new_group([0,3])`` per pipeline stage,
intro_PP_1F1B_MP.py:31-36) become mesh axes; its collectives become
``psum``/``ppermute`` over those axes.

Axis-name conventions used across the framework:
- ``data``    — data-parallel replicas (DP) / batch sharding
- ``stage``   — pipeline stages (PP)
- ``model``   — tensor-parallel shards (TP)
- ``seq``     — sequence/context parallelism (ring attention)
- ``clients`` — federated simulated clients
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a mesh with the given ``{axis_name: size}`` layout.

    With ``axes=None``, all devices go on a single ``data`` axis.  Axis sizes
    must multiply to the number of devices used; trailing axis of size 1 is
    allowed for single-device testing of multi-axis programs.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if axes is None:
        axes = {"data": len(devices)}
    total = math.prod(axes.values())
    if total > len(devices):
        raise ValueError(
            f"mesh axes {axes} need {total} devices, have {len(devices)}"
        )
    grid = np.array(devices[:total]).reshape(tuple(axes.values()))
    return Mesh(grid, tuple(axes.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded(mesh: Mesh, *axis_names) -> NamedSharding:
    """NamedSharding partitioning the leading dims along ``axis_names``."""
    return NamedSharding(mesh, P(*axis_names))
