"""Closed-loop load generator and saturation sweep for the serving
batcher.

The generator replays a SEEDED heavy-tailed arrival trace (lognormal or
Pareto inter-arrival gaps, unit mean, scaled to the offered QPS) against
a live :class:`~ddl25spring_tpu.models.serving.ContinuousBatcher` on the
wall clock: requests are submitted when their arrival time passes, the
batcher is stepped whenever work is in flight, and every completion is
stamped host-side.  It is closed-loop in the scheduling sense — the
generator and the batcher share one thread, so decode chunks and
admissions interleave exactly as a single-host serving loop would, and
queue growth feeds back into measured latency instead of being hidden
by an unbounded submission thread.

``saturation_sweep`` replays the same trace shape at increasing offered
QPS and reports one point per rate with goodput, latency percentiles,
queue wait, reject/evict rates and peak KV-page residency.  The knee is
the last offered rate the batcher still serves at >= ``knee_frac`` of
the offered load — past it, queue wait (and therefore latency) grows
without bound and extra offered load only converts to rejects.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["arrival_trace", "chaos_wrap", "replay", "replay_fleet",
           "saturation_sweep", "warm"]


def arrival_trace(nr: int, qps: float, dist: str = "lognormal",
                  seed: int = 0, *, sigma: float = 1.0,
                  alpha: float = 2.5) -> np.ndarray:
    """Absolute arrival times (seconds) for ``nr`` requests at an
    offered rate of ``qps``, with heavy-tailed inter-arrival gaps.

    Gaps are drawn with UNIT mean and divided by ``qps`` so the offered
    rate is exact in expectation whatever the tail shape:

    - ``"lognormal"``: ``exp(N(mu, sigma))`` with ``mu = -sigma**2/2``
      (the mean-one parameterisation).
    - ``"pareto"``: Lomax with shape ``alpha > 1`` scaled by
      ``alpha - 1`` (numpy's ``pareto(a)`` has mean ``1/(a-1)``).

    The trace is a deterministic function of ``(nr, qps, dist, seed)``
    and the tail parameters — sweeps at different rates reuse the same
    seed so every point replays the same burst STRUCTURE, only faster.
    """
    if nr < 1:
        raise ValueError(f"nr={nr} must be >= 1")
    if qps <= 0:
        raise ValueError(f"qps={qps} must be > 0")
    rng = np.random.default_rng(seed)
    if dist == "lognormal":
        gaps = rng.lognormal(mean=-0.5 * sigma * sigma, sigma=sigma,
                             size=nr)
    elif dist == "pareto":
        if alpha <= 1:
            raise ValueError(f"alpha={alpha} must be > 1 for a finite "
                             "mean")
        gaps = rng.pareto(alpha, size=nr) * (alpha - 1.0)
    else:
        raise ValueError(f"unknown arrival dist {dist!r}; expected "
                         "'lognormal' or 'pareto'")
    return np.cumsum(gaps / qps)


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs \
        else 0.0


def replay(batcher, trace, prompts, budgets, *,
           deadline_s: float | None = None) -> dict:
    """Replay one arrival trace through a live batcher and measure it.

    ``prompts[i]``/``budgets[i]`` arrive at ``trace[i]`` seconds after
    the replay starts.  Requests the batcher rejects (queue full, SLO,
    pool) are counted by reason and NOT retried — the sweep wants the
    reject rate at the offered load, not a retry storm.  Returns one
    point dict; see :func:`saturation_sweep` for the schema.
    """
    trace = np.asarray(trace, np.float64)
    nr = len(trace)
    if not (len(prompts) == len(budgets) == nr):
        raise ValueError(
            f"trace/prompts/budgets length mismatch: {nr} vs "
            f"{len(prompts)} vs {len(budgets)}")
    paged = getattr(batcher, "_paged", False)
    submit_t: dict = {}      # rid -> wall submit time
    admit_t: dict = {}       # rid -> wall admission time (left queue)
    waiting: set = set()     # submitted rids still in the batcher queue
    rejects: dict = {}       # reason -> count
    finished: dict = {}      # rid -> (latency_s, status, nr_tokens)
    tokens_out = 0
    pages_peak = 0

    def note_pages():
        # the pool's own high-water mark: step-boundary sampling misses
        # pages allocated and freed within one step() call
        nonlocal pages_peak
        if paged:
            pages_peak = max(pages_peak, batcher._pool.pages_peak)

    def mark_admitted(now):
        # a submitted rid that is no longer queued was admitted (or
        # resolved) this step; its queue wait ends here
        still = {q[0] for q in batcher._queue}
        for rid in [r for r in waiting if r not in still]:
            waiting.discard(rid)
            admit_t[rid] = now

    def absorb(done, now):
        nonlocal tokens_out
        for rid, toks in done.items():
            status = getattr(toks, "status", "ok")
            finished[rid] = (now - submit_t[rid], status, len(toks))
            tokens_out += len(toks)

    t0 = time.perf_counter()
    nxt = 0
    while nxt < nr or batcher.in_flight:
        now = time.perf_counter() - t0
        if nxt < nr and now >= trace[nxt]:
            rid = nxt
            try:
                submit_t[rid] = now
                batcher.submit(rid, list(prompts[nxt]),
                               int(budgets[nxt]), deadline_s=deadline_s)
                waiting.add(rid)
            except Exception as e:                # AdmissionRejected
                reason = getattr(e, "reason", None) or "rejected"
                rejects[reason] = rejects.get(reason, 0) + 1
                submit_t.pop(rid, None)
            nxt += 1
            continue
        if batcher.in_flight:
            done = batcher.step()
            now = time.perf_counter() - t0
            mark_admitted(now)
            note_pages()
            absorb(done, now)
        elif nxt < nr:
            time.sleep(min(0.002, max(0.0, trace[nxt] - now)))
    elapsed = max(time.perf_counter() - t0, 1e-9)
    note_pages()

    ok = [lat for lat, status, _ in finished.values() if status == "ok"]
    lats = [lat for lat, _, _ in finished.values()]
    waits = [admit_t[r] - submit_t[r] for r in admit_t if r in submit_t]
    evicted = sum(1 for _, status, _ in finished.values()
                  if status != "ok")
    nr_rej = sum(rejects.values())
    return {
        "offered_qps": nr / float(trace[-1]),
        "elapsed_s": elapsed,
        "completed": len(finished),
        "goodput_rps": len(ok) / elapsed,
        "tokens_per_sec": tokens_out / elapsed,
        "latency_p50_s": _pct(lats, 50),
        "latency_p99_s": _pct(lats, 99),
        "queue_wait_p50_s": _pct(waits, 50),
        "queue_wait_p99_s": _pct(waits, 99),
        "reject_rate": nr_rej / nr,
        "rejects_by_reason": dict(sorted(rejects.items())),
        "evict_rate": evicted / nr,
        "kv_pages_peak": pages_peak,
    }


def replay_fleet(router, trace, prompts, budgets, *,
                 deadline_s: float | None = None) -> dict:
    """Fleet replay mode: :func:`replay` driven through a
    ``serving_fleet.FleetRouter`` (which exposes the same
    submit/step/in_flight surface as one batcher), extended with the
    routing view a fleet point needs — per-replica completion counts and
    page peaks, requests routed/re-routed, and re-routes by rejection
    reason.  The base point's ``kv_pages_peak`` is the SUM of per-replica
    pool peaks (the fleet's resident-KV high-water bound)."""
    routed0 = router.stats["routed"]
    rerouted0 = router.stats["rerouted"]
    by0 = dict(router.stats["rerouted_by_reason"])
    fo0 = router.stats.get("failed_over", 0)
    tr0 = router.stats.get("failover_tokens_replayed", 0)
    rf0 = router.stats.get("replicas_failed", 0)
    pt = replay(router, trace, prompts, budgets, deadline_s=deadline_s)
    assigned = router.assignments()
    pt["replicas"] = len(router.replicas)
    pt["routed"] = router.stats["routed"] - routed0
    pt["rerouted"] = router.stats["rerouted"] - rerouted0
    pt["rerouted_by_reason"] = {
        k: v - by0.get(k, 0)
        for k, v in sorted(router.stats["rerouted_by_reason"].items())
        if v - by0.get(k, 0)
    }
    pt["failed_over"] = router.stats.get("failed_over", 0) - fo0
    pt["failover_tokens_replayed"] = (
        router.stats.get("failover_tokens_replayed", 0) - tr0)
    pt["replicas_failed"] = router.stats.get("replicas_failed", 0) - rf0
    pt["per_replica"] = [
        {
            "assigned": len(assigned.get(i, ())),
            "queue_len": len(r._queue),
            "kv_pages_peak": (r._pool.pages_peak
                              if getattr(r, "_pool", None) is not None
                              else 0),
        }
        for i, r in enumerate(router.replicas)
    ]
    return pt


def warm(make_batcher, prompts, budgets, *,
         deadline_s: float | None = None) -> None:
    """Compile every program shape a replay can hit, outside the timed
    points.  Admissions pad the group to a power of two, so a burst
    trace only compiles the full-group admit — a request trickling in
    alone at low offered rate would then eat the G=1 compile inside a
    measured point.  One batcher replays each power-of-two group size
    up to ``max_batch``; the program cache is keyed on shapes, so every
    later batcher of the same shape runs warm.  That includes every
    replica of a fleet: warm ONE replica-shaped batcher and all N
    replicas behind a ``FleetRouter`` reuse the same compiled set (a
    router passed here also works — its duck surface matches — but
    warming one replica is N times cheaper)."""
    wb = make_batcher()
    mb = max(1, int(getattr(wb, "max_batch", 1)))
    g = 1
    while g <= min(mb, len(prompts)):
        replay(wb, arrival_trace(g, 1e4, "lognormal", 0), prompts[:g],
               budgets[:g], deadline_s=deadline_s)
        g *= 2


def chaos_wrap(router, schedule):
    """Wrap every replica of a ``FleetRouter`` in the seeded
    :class:`~ddl25spring_tpu.resilience.faults.FaultyReplica` chaos
    wrapper, in place.  Replica-level chaos needs a fleet — a crashed
    single batcher has nothing to fail over to."""
    from ..resilience.faults import FaultyReplica

    if not hasattr(router, "replicas"):
        raise ValueError(
            "chaos replay needs a FleetRouter (something with "
            ".replicas) — a single batcher cannot fail over")
    router.replicas = [FaultyReplica(r, schedule, i)
                       for i, r in enumerate(router.replicas)]
    return router


def saturation_sweep(make_batcher, qps_points, nr_requests, prompt_fn,
                     budget, *, dist: str = "lognormal", seed: int = 0,
                     deadline_s: float | None = None,
                     knee_frac: float = 0.9,
                     warmup: bool = True,
                     replay_fn=None, chaos=None) -> dict:
    """Replay the same seeded trace shape at each offered rate in
    ``qps_points`` (ascending) against a FRESH batcher per point from
    ``make_batcher()`` — program caches inside the batcher make the
    rebuild cheap, and a fresh queue/pool per point keeps the points
    independent.

    ``prompt_fn(i, rng)`` produces request ``i``'s token list from a
    per-sweep ``numpy`` generator, so the workload is identical across
    points.  The knee is the LAST point whose goodput is at least
    ``knee_frac`` of the offered rate; past it the batcher is saturated
    and queue wait grows with offered load instead of goodput.

    ``replay_fn`` swaps the per-point measurement (default
    :func:`replay`); pass :func:`replay_fleet` with a ``make_batcher``
    that builds a ``FleetRouter`` to sweep a fleet — every point then
    also carries the routing view.

    ``chaos`` (a ``resilience.ReplicaFaultSchedule``) adds one EXTRA
    replay at the measured knee rate with every replica wrapped in the
    seeded fault injector (:func:`chaos_wrap`): the result grows a
    ``"chaos"`` block reporting goodput-under-chaos next to the clean
    knee, plus the failover/replay counters and the faults actually
    injected.  Fleet-only (``replay_fn=replay_fleet``).
    """
    qps_points = sorted(float(q) for q in qps_points)
    rng = np.random.default_rng(seed)
    prompts = [prompt_fn(i, rng) for i in range(nr_requests)]
    budgets = [int(budget)] * nr_requests
    if warmup:
        warm(make_batcher, prompts, budgets, deadline_s=deadline_s)
    measure = replay if replay_fn is None else replay_fn
    points = []
    for qps in qps_points:
        trace = arrival_trace(nr_requests, qps, dist, seed)
        batcher = make_batcher()
        points.append(measure(batcher, trace, prompts, budgets,
                              deadline_s=deadline_s))
    knee = None
    knee_pt = None
    for pt in points:
        if pt["goodput_rps"] >= knee_frac * pt["offered_qps"]:
            knee = pt["offered_qps"]
            knee_pt = pt
    out = {"dist": dist, "seed": seed, "nr_requests": nr_requests,
           "knee_qps": knee, "knee_frac": knee_frac, "points": points}
    if chaos is not None:
        qps = knee if knee is not None else qps_points[0]
        trace = arrival_trace(nr_requests, qps, dist, seed)
        router = chaos_wrap(make_batcher(), chaos)
        pt = measure(router, trace, prompts, budgets,
                     deadline_s=deadline_s)
        injected: dict = {}
        for r in router.replicas:
            for k, v in getattr(r, "fault_counts", {}).items():
                if v:
                    injected[k] = injected.get(k, 0) + v
        clean = knee_pt["goodput_rps"] if knee_pt else None
        out["chaos"] = {
            "schedule": chaos.describe(),
            "at_qps": qps,
            "goodput_rps": pt["goodput_rps"],
            "goodput_frac_of_clean": (pt["goodput_rps"] / clean
                                      if clean else None),
            "faults_injected": dict(sorted(injected.items())),
            "point": pt,
        }
    return out
