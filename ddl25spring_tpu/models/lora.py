"""LoRA — low-rank adaptation of the Llama matmuls (Hu et al., public).

The reference never fine-tunes anything; with the HF weight bridge
(tools/import_hf_llama.py) this framework serves published checkpoints,
and LoRA is the canonical way to ADAPT one without touching its weights:
every matmul ``x @ W`` becomes ``x @ W + (alpha/r) * (x @ A) @ B`` with
``A`` (in, r) small-random and ``B`` (r, out) ZERO — so an adapted model
is exactly the base model at init, and training only moves the ~r·(in+out)
adapter params per layer (optimizer state shrinks by the same factor).

Three pieces, all config-driven:

- ``LlamaConfig(lora_rank=r)`` swaps every matmul for :class:`LoRADense`
  (models/llama.py ``_dense_cls``) — base kernels stay in the tree, so an
  imported checkpoint loads unchanged and a frozen-base optimizer mask
  keeps it bit-identical;
- :func:`lora_trainable_mask` marks exactly the adapter leaves for
  ``optax.masked`` (the standard freeze);
- :func:`merge_lora` folds ``(alpha/r)·A@B`` into the kernels and returns
  a plain (lora_rank=0) tree for serving — zero inference overhead, and
  the merged model then composes with int8 quantization, TP shardings,
  speculative decoding, everything.

Multi-tenant serving adds a fourth piece: ``LlamaConfig(lora_slots=N)``
swaps every matmul for :class:`MultiLoRADense`, which stacks N adapters
next to ONE shared base kernel and gathers ``(A_i, B_i, scale_i)`` per
batch row at call time — ``x@W + scale_i*(x@A_i)@B_i`` with a hard
``jnp.where`` guard so rows carrying slot 0 (the reserved null adapter)
return the base matmul BITWISE, not just within float tolerance.  The
wire format between training and the stacks is
:func:`slice_adapter` / :func:`apply_adapter` (adapter-subtree extract /
re-attach, byte-identical round trip), and
:func:`stack_adapter_params` / :func:`install_adapter` convert a plain
serving tree into the stacked layout and hot-write one tenant's factors
into a slot (the ``models/adapter_pool.AdapterPool`` install path).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class LoRADense(nn.Module):
    """``x @ kernel + (alpha/rank) * (x @ lora_A) @ lora_B`` (no bias)."""

    features: int
    rank: int
    alpha: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (in_dim, self.features),
        ).astype(self.dtype)
        a = self.param(
            "lora_A", nn.initializers.normal(0.01), (in_dim, self.rank)
        ).astype(self.dtype)
        b = self.param(
            "lora_B", nn.initializers.zeros, (self.rank, self.features)
        ).astype(self.dtype)
        x = x.astype(self.dtype)
        return x @ kernel + (self.alpha / self.rank) * ((x @ a) @ b)


class MultiLoRADense(nn.Module):
    """One shared base kernel + ``nr_slots`` stacked LoRA adapters.

    ``lora_A`` is ``(nr_slots, in, rank)``, ``lora_B`` is
    ``(nr_slots, rank, features)`` and ``lora_scale`` is ``(nr_slots,)``
    — all ZERO at init, so every slot starts as the null adapter and
    real tenants are written in with :func:`install_adapter`.  The call
    takes per-row ``slots`` (int32 ``(batch,)``); each row gathers its
    own factors and computes ``x@W + scale_i*(x@A_i)@B_i``.  Slot 0 is
    RESERVED as the null adapter: rows carrying it are routed through a
    ``jnp.where`` onto the bare base matmul, so a null row is bit-
    identical to the base model even when ``base + 0.0`` would not be
    (``-0.0 + 0.0`` rounds to ``+0.0``).  ``slots=None`` skips the
    adapter math entirely (training / non-serving callers).
    """

    features: int
    rank: int
    nr_slots: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, slots=None):
        in_dim = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (in_dim, self.features),
        ).astype(self.dtype)
        a = self.param(
            "lora_A", nn.initializers.zeros,
            (self.nr_slots, in_dim, self.rank),
        ).astype(self.dtype)
        b = self.param(
            "lora_B", nn.initializers.zeros,
            (self.nr_slots, self.rank, self.features),
        ).astype(self.dtype)
        scale = self.param(
            "lora_scale", nn.initializers.zeros, (self.nr_slots,)
        ).astype(self.dtype)
        x = x.astype(self.dtype)
        base = x @ kernel
        if slots is None:
            return base
        # per-row gather, then the two-step low-rank product — (x@A)@B is
        # O(T·r·(in+out)) where fusing A@B first would be O(in·out)
        a_i = jnp.take(a, slots, axis=0)            # (B, in, r)
        b_i = jnp.take(b, slots, axis=0)            # (B, r, out)
        s_i = jnp.take(scale, slots, axis=0)        # (B,)
        delta = jnp.einsum("btd,bdr->btr", x, a_i)
        delta = jnp.einsum("btr,bro->bto", delta, b_i)
        out = base + s_i[:, None, None] * delta
        return jnp.where((slots == 0)[:, None, None], base, out)


def lora_trainable_mask(params):
    """Boolean pytree: True exactly on ``lora_A``/``lora_B`` leaves — feed
    ``optax.masked(opt, mask)`` to freeze the base model."""

    def mark(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", "")) for k in path]
        return names[-1] in ("lora_A", "lora_B")

    return jax.tree_util.tree_map_with_path(mark, params)


def make_lora_optimizer(base_optimizer):
    """Wrap an optax optimizer so ONLY adapter params receive updates.

    ``optax.masked`` alone would pass the base params' raw gradients
    through untouched (its contract is pass-through, not freeze);
    ``multi_transform`` routes adapters to the real optimizer and
    everything else to ``set_to_zero`` — the base model stays
    bit-identical through training (tests pin this) and optimizer state
    is sized for the adapters only.
    """

    def labels(tree):
        return jax.tree.map(
            lambda m: "train" if m else "freeze", lora_trainable_mask(tree)
        )

    return optax.multi_transform(
        {"train": base_optimizer, "freeze": optax.set_to_zero()}, labels
    )


def merge_lora(params, config):
    """Fold each adapter into its kernel; -> plain lora_rank=0 tree.

    The merged tree loads into ``LlamaConfig(lora_rank=0)`` (or int8 via
    quantize_llama_params, TP via llama_tp_shardings, ...) with the
    adapted behaviour baked in and zero inference overhead.
    """
    scale = config.lora_alpha / config.lora_rank

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if isinstance(sub, dict) and "lora_A" in sub:
                merged = sub["kernel"] + scale * (
                    sub["lora_A"] @ sub["lora_B"]
                )
                out[name] = {"kernel": merged}
            elif isinstance(sub, dict):
                out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    return {k: walk(v) for k, v in params.items()}


# -- adapter wire format -------------------------------------------------
#
# slice_adapter / apply_adapter define THE interchange format between the
# FL side (rounds over the adapter subtree only), the rollout plane
# (adapter-kind ParamBundles) and the serving AdapterPool (install into a
# MultiLoRADense slot): a nested dict mirroring the params tree that
# keeps exactly the dicts holding lora_A/lora_B and nothing else.


def slice_adapter(params):
    """Extract ONLY the ``lora_A``/``lora_B`` leaves of a LoRA tree,
    keeping the enclosing dict structure (branches without adapters are
    pruned).  The result is the adapter wire format: what an FL round
    trains, what a bundle carries, what :func:`install_adapter` writes
    into a pool slot.  ``apply_adapter(params, slice_adapter(params))``
    is byte-identical to ``params`` (the leaves are the same arrays)."""

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if not isinstance(sub, dict):
                continue
            if "lora_A" in sub:
                out[name] = {"lora_A": sub["lora_A"],
                             "lora_B": sub["lora_B"]}
            else:
                w = walk(sub)
                if w:
                    out[name] = w
        return out

    return walk(params)


def apply_adapter(base, adapter):
    """Re-attach a :func:`slice_adapter` subtree onto ``base``: adapter
    leaves replace the matching ``lora_A``/``lora_B`` leaves, every
    other leaf passes through untouched.  Raises when an adapter path
    has no LoRA site in ``base`` — a silently dropped tenant delta is
    the failure mode this wire format exists to prevent."""

    def walk(b, a, path):
        unknown = set(a) - set(b)
        if unknown:
            raise ValueError(
                f"adapter path {path}/{sorted(unknown)[0]} not in base "
                "params (rank/config mismatch?)")
        out = {}
        for name, sub in b.items():
            if name not in a:
                out[name] = sub
            elif "lora_A" in a[name]:
                if not (isinstance(sub, dict) and "lora_A" in sub):
                    raise ValueError(
                        f"{path}/{name} is not a LoRA site in base")
                out[name] = {**sub, "lora_A": a[name]["lora_A"],
                             "lora_B": a[name]["lora_B"]}
            else:
                out[name] = walk(sub, a[name], f"{path}/{name}")
        return out

    return walk(base, adapter, "")


def stack_adapter_params(params, config):
    """Convert a plain serving tree (``kernel``-only dense sites) into
    the :class:`MultiLoRADense` stacked layout for
    ``LlamaConfig(lora_slots=N)``: every dict holding a ``kernel`` gains
    zero ``lora_A (N, in, r)`` / ``lora_B (N, r, out)`` /
    ``lora_scale (N,)`` stacks (all slots start null).  Trees that
    already carry per-module adapters must be :func:`merge_lora`-d
    first — stacking would silently drop them."""
    n, r = config.lora_slots, config.lora_rank

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if isinstance(sub, dict) and "kernel" in sub:
                if "lora_scale" in sub:
                    out[name] = sub          # already stacked
                    continue
                if "lora_A" in sub:
                    raise ValueError(
                        "params already carry per-module LoRA adapters; "
                        "merge_lora them before stacking")
                k = sub["kernel"]
                out[name] = {
                    **sub,
                    "lora_A": jnp.zeros((n, k.shape[0], r), k.dtype),
                    "lora_B": jnp.zeros((n, r, k.shape[1]), k.dtype),
                    "lora_scale": jnp.zeros((n,), k.dtype),
                }
            elif isinstance(sub, dict):
                out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    return {k: (walk(v) if isinstance(v, dict) else v)
            for k, v in params.items()}


def install_adapter(stacked, slot, adapter, scale):
    """Write one tenant's :func:`slice_adapter` factors into ``slot`` of
    a :func:`stack_adapter_params` tree (functional: returns a new tree
    touching only the stacked leaves).  ``scale`` is the tenant's
    ``alpha/rank``.  Slot 0 is the reserved null adapter and refuses
    installs — its all-zero stacks back the bitwise base-model
    contract."""
    if slot == 0:
        raise ValueError("slot 0 is the reserved null adapter")

    def walk(s, a, path):
        unknown = set(a) - set(s)
        if unknown:
            raise ValueError(
                f"adapter path {path}/{sorted(unknown)[0]} not in "
                "stacked params")
        out = {}
        for name, sub in s.items():
            if name not in a:
                out[name] = sub
            elif "lora_A" in a[name]:
                if "lora_scale" not in sub:
                    raise ValueError(
                        f"{path}/{name} is not a stacked LoRA site")
                aa = jnp.asarray(a[name]["lora_A"],
                                 sub["lora_A"].dtype)
                bb = jnp.asarray(a[name]["lora_B"],
                                 sub["lora_B"].dtype)
                # the stacks may be numpy (a ParamBundle-applied tree
                # coming back through the rollout plane) — .at needs jnp
                out[name] = {
                    **sub,
                    "lora_A": jnp.asarray(sub["lora_A"]).at[slot].set(aa),
                    "lora_B": jnp.asarray(sub["lora_B"]).at[slot].set(bb),
                    "lora_scale": jnp.asarray(
                        sub["lora_scale"]).at[slot].set(scale),
                }
            else:
                out[name] = walk(sub, a[name], f"{path}/{name}")
        return out

    return walk(stacked, adapter, "")
