"""Party-sharded vertical FL: the activation cut as an ICI all-gather.

The reference's VFL concatenates per-party bottom activations in-process
(``torch.cat(local_outs, dim=1)``, lab/tutorial_2b/vfl.py:36).  In a real
deployment that concat is the network boundary: each party ships its
activation block to the server.  The TPU-native rendering (SURVEY.md §2.2)
puts each party on its own slice of a ``party`` mesh axis: bottoms run
party-parallel on their local feature shards, and the concat lowers to ONE
XLA all-gather over ICI, inserted by GSPMD at the sharding boundary between
the party-sharded activation stack and the replicated top model.

Differences from :class:`~ddl25spring_tpu.vfl.splitnn.VFLNetwork` (the
in-process simulation, kept for reference-shaped heterogeneous parties):

- Party bottoms share one architecture and a common padded feature width, so
  parameters stack into a leading party axis and shard cleanly.  Padded
  feature columns are constant zero, so their Dense weight rows neither
  affect the forward nor receive gradient — padding is exact, not
  approximate (``tests/test_vfl.py::test_padded_equals_heterogeneous``).
- Execution is identical with or without a mesh: the mesh only adds
  ``with_sharding_constraint`` annotations, so the sharded program is
  bit-equivalent to the local one
  (``tests/test_vfl.py::test_party_sharded_equals_local``).

Backward pass: ``jax.grad`` through the gather gives each party exactly the
gradient block of its own activations (the transpose of all-gather is
reduce-scatter) — the server->client gradient message of real split
learning, again as one collective over ICI.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.lax import with_sharding_constraint
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.losses import cross_entropy_logits
from .splitnn import BottomModel, TopModel


def stack_party_inputs(x, feature_slices, pad_to: int | None = None):
    """Stack per-party feature blocks into one ``(P, B, f_pad)`` array.

    ``x`` is the full ``(B, F)`` table; each party's columns (its
    ``feature_slices`` entry) land left-aligned in a zero-padded row of
    width ``pad_to`` (default: the widest party).  Zero padding is exact for
    Dense bottoms (zero inputs contribute nothing forward or backward).
    """
    x = np.asarray(x, np.float32)
    widths = [len(sl) for sl in feature_slices]
    f_pad = max(widths) if pad_to is None else pad_to
    if f_pad < max(widths):
        raise ValueError(f"pad_to={pad_to} < widest party ({max(widths)})")
    out = np.zeros((len(feature_slices), x.shape[0], f_pad), np.float32)
    for i, sl in enumerate(feature_slices):
        out[i, :, : widths[i]] = x[:, sl]
    return jnp.asarray(out)


@dataclass
class PartyShardedVFL:
    """Split network with bottoms sharded over a ``party`` mesh axis.

    ``mesh`` must carry a ``party`` axis whose size divides the number of
    parties (parties fold onto devices in equal groups).  ``mesh=None`` runs
    the identical program unsharded — the test oracle.
    """

    feature_slices: list  # per-party column index arrays into x
    out_dim: int = 32  # shared bottom output width
    nr_classes: int = 2
    seed: int = 42
    lr: float = 1e-3
    mesh: Mesh | None = None
    bottom: BottomModel = field(init=False)
    top: TopModel = field(init=False)

    def __post_init__(self):
        self.nr_parties = len(self.feature_slices)
        self.f_pad = max(len(sl) for sl in self.feature_slices)
        if self.mesh is not None:
            if "party" not in self.mesh.axis_names:
                raise ValueError("mesh needs a 'party' axis")
            if self.nr_parties % self.mesh.shape["party"]:
                raise ValueError(
                    f"{self.nr_parties} parties not divisible by party-axis "
                    f"size {self.mesh.shape['party']}"
                )
        self.bottom = BottomModel(self.out_dim)
        self.top = TopModel(self.nr_classes)
        self.optimizer = optax.adamw(self.lr)

        key = jax.random.key(self.seed)
        bkeys = jax.random.split(key, self.nr_parties + 2)
        dummy = jnp.zeros((1, self.f_pad))
        per_party = [self.bottom.init(bkeys[i], dummy)
                     for i in range(self.nr_parties)]
        bottoms = jax.tree.map(lambda *xs: jnp.stack(xs), *per_party)
        top = self.top.init(
            bkeys[-2], jnp.zeros((1, self.nr_parties * self.out_dim))
        )
        self.params = {"bottoms": bottoms, "top": top}
        self.opt_state = self.optimizer.init(self.params)
        self.dropout_key = bkeys[-1]
        self._step = jax.jit(self._make_step())
        self._fwd = jax.jit(
            lambda p, xs: self._forward(p, xs, train=False, key=None)
        )

    # -- sharding annotations ------------------------------------------------
    def _party(self, tree):
        """Constrain leading (party) axis onto the mesh; no-op without one."""
        if self.mesh is None:
            return tree
        s = NamedSharding(self.mesh, P("party"))
        return jax.tree.map(lambda a: with_sharding_constraint(a, s), tree)

    def _repl(self, tree):
        if self.mesh is None:
            return tree
        s = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda a: with_sharding_constraint(a, s), tree)

    # -- the split forward ---------------------------------------------------
    def _forward(self, params, x_stacked, *, train: bool, key):
        """``x_stacked``: (P, B, f_pad).  Party-parallel bottoms, all-gather
        cut, replicated top."""
        bottoms = self._party(params["bottoms"])
        xs = self._party(x_stacked)
        if train:
            pkeys = jax.vmap(
                lambda i: jax.random.fold_in(key, i)
            )(jnp.arange(self.nr_parties))

            def one(bp, xp, k):
                return self.bottom.apply(
                    bp, xp, train=True, rngs={"dropout": k}
                )

            acts = jax.vmap(one)(bottoms, xs, pkeys)
        else:
            acts = jax.vmap(
                lambda bp, xp: self.bottom.apply(bp, xp, train=False)
            )(bottoms, xs)
        acts = self._party(acts)  # (P, B, out) party-sharded: pre-cut state
        # THE CUT: party-major flatten to (B, P*out).  The operand is
        # party-sharded, the result consumed replicated — GSPMD lowers the
        # resharding to one all-gather over the party axis (ICI), the exact
        # analogue of each party shipping its activation block to the server
        # (reference torch.cat, vfl.py:36).
        concat = acts.transpose(1, 0, 2).reshape(
            acts.shape[1], self.nr_parties * self.out_dim
        )
        concat = self._repl(concat)
        kw = (
            {"rngs": {"dropout": jax.random.fold_in(key, self.nr_parties)}}
            if train else {}
        )
        return self.top.apply(params["top"], concat, train=train, **kw)

    def _make_step(self):
        def loss_fn(params, xs, y_onehot, key):
            logits = self._forward(params, xs, train=True, key=key)
            return cross_entropy_logits(logits, y_onehot)

        def step(params, opt_state, xs, y_onehot, key):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, xs, y_onehot, key
            )
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            return optax.apply_updates(params, updates), opt_state, loss

        return step

    # -- reference-shaped API ------------------------------------------------
    def train_with_settings(self, epochs: int, batch_size: int, x, y_onehot,
                            log_every: int = 0, log_loss=None):
        """Sequential minibatches, no shuffling (vfl.py:53-85 shape)."""
        xs = stack_party_inputs(x, self.feature_slices, self.f_pad)
        y = jnp.asarray(y_onehot, jnp.float32)
        n = xs.shape[1]
        nr_batches = -(-n // batch_size)
        history = []
        for epoch in range(epochs):
            total = 0.0
            for b in range(nr_batches):
                sl = slice(b * batch_size, min((b + 1) * batch_size, n))
                key, self.dropout_key = jax.random.split(self.dropout_key)
                self.params, self.opt_state, loss = self._step(
                    self.params, self.opt_state, xs[:, sl], y[sl], key
                )
                total += float(loss)
            history.append(total / nr_batches)
            if log_loss is not None:
                log_loss(epoch, history[-1])
            if log_every and epoch % log_every == 0:
                print(f"Epoch: {epoch} Loss: {history[-1]:.3f}")
        return history

    def test(self, x, y_onehot):
        xs = stack_party_inputs(x, self.feature_slices, self.f_pad)
        y = jnp.asarray(y_onehot, jnp.float32)
        logits = self._fwd(self.params, xs)
        pred = jnp.argmax(logits, axis=1)
        acc = jnp.mean((pred == jnp.argmax(y, axis=1)).astype(jnp.float32))
        return float(acc), float(cross_entropy_logits(logits, y))
