"""Tensor-parallel serving replica: llama decode sharded over a
``model`` mesh axis with the paged KV pool partitioned along KV heads.

The batcher's compiled programs are UNCHANGED — TP is pure data
placement, the GSPMD discipline of ``parallel/tp.py``: params get the
Megatron column/row shardings, every KV cache/pool leaf shards its head
axis (pool leaves become ``(nr_pages, kv_page, Hkv/W, hd)`` per shard,
int8 scale planes ``(nr_pages, kv_page, Hkv/W)``), and the block
tables / token / pos / pad vectors stay replicated.  jit re-specializes
the same lru-cached admit/decode programs on the input shardings and
XLA inserts the collectives; attention itself needs NONE (heads are
independent — the only cross-shard reduces are the Megatron row-matmul
psums).  At ``W=1`` the annotations are no-ops, so the sharded batcher
is bit-identical to today's paged batcher by construction.

``decode_impl`` is pinned to ``"xla"`` for ``W > 1``: a ``pallas_call``
inside a GSPMD-partitioned jit cannot be auto-sharded.  The flash-decode
kernel still covers TP through :func:`headsharded_flash_decode` — a
``shard_map`` wrapper that runs the UNMODIFIED paged kernel per shard on
its own head slice (legal because the kernel's head loop is static and
heads never interact), validated head-slice-for-head-slice against the
full-pool kernel in tier-1 tests.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.serving import ContinuousBatcher
from ..ops.flash_decode import flash_decode_attention
from ..parallel.compat import shard_map
from ..parallel.mesh import make_mesh
from ..parallel.tp import apply_shardings, llama_tp_shardings

__all__ = ["TPShardedBatcher", "headsharded_flash_decode",
           "make_model_mesh"]


def make_model_mesh(world: int, *, axis: str = "model", devices=None):
    """A 1-D mesh of ``world`` devices on the ``model`` axis."""
    if world < 1:
        raise ValueError(f"tp world must be >= 1, got {world}")
    return make_mesh({axis: world}, devices=devices)


def kv_head_sharding(mesh, leaf, *, axis: str = "model") -> NamedSharding:
    """Sharding for one KV cache/pool leaf: partition the head axis
    (axis 2 in both the contiguous ``(B, S, Hkv, hd)`` and paged
    ``(nr_pages, kv_page, Hkv[, hd])`` layouts) when divisible,
    replicate otherwise (a non-divisible head count still serves — it
    just forgoes the pool split)."""
    W = mesh.shape[axis]
    shape = getattr(leaf, "shape", ())
    if len(shape) >= 3 and shape[2] % W == 0:
        return NamedSharding(
            mesh, P(*((None, None, axis) + (None,) * (len(shape) - 3))))
    return NamedSharding(mesh, P())


class TPShardedBatcher(ContinuousBatcher):
    """:class:`ContinuousBatcher` with params and KV state sharded over
    a ``model`` mesh axis.

    ``tp_world`` picks the first N local devices (or pass a prebuilt
    ``mesh`` that has ``model_axis``).  Requires ``nr_heads`` and the KV
    head count divisible by the world size — GQA group structure must
    survive the split (each shard keeps whole ``Hq/W : Hkv/W`` groups).
    Everything else — queue, pool accounting, admission control, block
    tables — is host state and identical to the base batcher, which is
    what lets the ``FleetRouter`` mix sharded and unsharded replicas.
    """

    def __init__(self, config, params, *, mesh=None,
                 tp_world: int | None = None, model_axis: str = "model",
                 **kwargs):
        if mesh is None:
            mesh = make_model_mesh(tp_world or 1, axis=model_axis)
        if model_axis not in mesh.shape:
            raise ValueError(
                f"mesh axes {dict(mesh.shape)} lack the model axis "
                f"{model_axis!r}")
        W = int(mesh.shape[model_axis])
        kv_heads = config.nr_kv_heads or config.nr_heads
        if W > 1:
            if kwargs.get("adapter_slots", 0):
                raise NotImplementedError(
                    "adapter_slots over a TP-sharded replica: the stacked "
                    "LoRA factors need their own layout (lora_A "
                    "replicated, lora_B sharded on the output axis like "
                    "the dense kernel it corrects) plus a sharded "
                    "install_adapter — multi-LoRA on the TP replica is "
                    "future work; run adapter serving on single-shard "
                    "replicas behind the fleet router for now")
            if kwargs.get("spill", "off") != "off":
                raise NotImplementedError(
                    "spill='host' over a head-sharded pool: parking "
                    "device_gets and re-uploads whole pool pages, which "
                    "would gather/rescatter every shard through the host "
                    "— spill on the TP replica is future work (kv_dtype "
                    "including int8 composes fine: the scale planes "
                    "shard on the same head axis)")
            if config.nr_heads % W or kv_heads % W:
                raise ValueError(
                    f"nr_heads={config.nr_heads} / kv_heads={kv_heads} "
                    f"must both divide by the tp world {W} (whole GQA "
                    "groups per shard)")
            # pallas_call does not partition under GSPMD — pin the einsum
            # decode path; the per-shard flash kernel lives in
            # headsharded_flash_decode (shard_map, TPU serving path)
            config = dataclasses.replace(config, decode_impl="xla")
        self.mesh = mesh
        self.model_axis = model_axis
        self.tp_world = W
        params = apply_shardings(
            params, llama_tp_shardings(mesh, params, model_axis))
        super().__init__(config, params, **kwargs)
        # shard the serving state the programs thread through every
        # dispatch: KV pool/cache on heads, scheduler vectors replicated
        repl = NamedSharding(mesh, P())
        shard_kv = lambda leaf: jax.device_put(
            leaf, kv_head_sharding(mesh, leaf, axis=model_axis))
        self.cache = jax.tree.map(shard_kv, self.cache)
        if self._prefix_cache is not None:
            self._prefix_cache = jax.tree.map(shard_kv, self._prefix_cache)
        self.tokens = jax.device_put(self.tokens, repl)
        self.pos = jax.device_put(self.pos, repl)
        self.pad = jax.device_put(self.pad, repl)

    def kv_shard_shapes(self) -> list:
        """Per-device shapes of the sharded KV leaves (what ``--tp-kv``
        cross-checks AOT): head axis divided by the world size."""
        return [s.data.shape for leaf in jax.tree.leaves(self.cache)
                for s in leaf.addressable_shards[:1]]


def headsharded_flash_decode(mesh, q, cache_k, cache_v, pos, pad=None, *,
                             block_tables=None, prefix_len: int = 0,
                             cache_k_scale=None, cache_v_scale=None,
                             model_axis: str = "model",
                             interpret: bool | None = None):
    """The paged flash-decode kernel over a head-sharded pool: each
    shard runs the UNCHANGED ``ops/flash_decode.py`` kernel on its own
    ``Hkv/W`` pool slice and ``Hq/W`` query slice; outputs concatenate
    over heads with no collective (attention heads are independent, so
    the head split is communication-free — the Megatron psums live in
    the surrounding matmuls, not here)."""
    W = int(mesh.shape[model_axis])
    Hq = q.shape[1]
    Hkv = cache_k.shape[2]
    if Hq % W or Hkv % W:
        raise ValueError(
            f"Hq={Hq} / Hkv={Hkv} must divide by the model-axis size {W}")
    head2 = P(None, model_axis, None)        # q / out: (B, Hq, hd)
    pool = P(None, None, model_axis, None)   # (pages|B, kv_page|S, Hkv, hd)
    scale = P(None, None, model_axis)        # int8 scale planes
    args = [q, cache_k, cache_v, pos]
    in_specs = [head2, pool, pool, P()]
    if pad is not None:
        args.append(pad)
        in_specs.append(P())
    if cache_k_scale is not None:
        args += [cache_k_scale, cache_v_scale]
        in_specs += [scale, scale]
    if block_tables is not None:
        args.append(block_tables)
        in_specs.append(P())  # tables replicated: every shard reads all

    def body(q_, k_, v_, pos_, *rest):
        rest = list(rest)
        pad_ = rest.pop(0) if pad is not None else None
        ks_ = rest.pop(0) if cache_k_scale is not None else None
        vs_ = rest.pop(0) if cache_k_scale is not None else None
        tables_ = rest.pop(0) if block_tables is not None else None
        return flash_decode_attention(
            q_, k_, v_, pos_, pad_, cache_k_scale=ks_, cache_v_scale=vs_,
            prefix_len=prefix_len, block_tables=tables_,
            interpret=interpret)

    return shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=head2,
        check_vma=False,
    )(*args)
