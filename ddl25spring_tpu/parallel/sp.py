"""Sequence/context parallelism (ring attention over a ``seq`` mesh axis).

Long-context training the reference cannot do at all: its context is fixed at
seq_l=256 (lab/tutorial_1b/primer/intro.py:10) and it has no sequence-scaling
mechanism (SURVEY.md §5).  Here the sequence dimension of every activation is
sharded over a ``seq`` mesh axis; attention runs blockwise over a ppermute
ring (ops.attention.ring_causal_attention), so per-device attention memory is
O(T²/S²) and KV blocks ride the ICI ring.  Everything else in the block
(RMSNorm, SwiGLU, QKV projections) is pointwise over the sequence, so it
needs no communication at all.

Composes with data parallelism on a 2-D ``(data, seq)`` mesh: batch sharded
over ``data``, sequence over ``seq``.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.llama import Llama, LlamaConfig
from ..ops.losses import causal_lm_loss


def make_sp_forward(config: LlamaConfig, mesh, seq_axis: str = "seq",
                    data_axis: str | None = None):
    """``forward(params, tokens) -> logits`` with the sequence dimension of
    ``tokens``/activations sharded over ``seq_axis``; params replicated.

    ``tokens`` is global (B, T); T must divide by the seq-axis size.
    """
    # "flash" (or explicit "ring-flash") upgrades the ring's per-step block
    # attention from dense XLA einsums to the Pallas kernels
    # (ops/ring_flash.py); "dense"/"ring" keep the einsum ring.
    ring_impl = (
        "ring-flash" if config.attn_impl in ("flash", "ring-flash") else "ring"
    )
    sp_config = dataclasses.replace(config, attn_impl=ring_impl,
                                    seq_axis=seq_axis)
    model = Llama(sp_config)
    batch = data_axis  # None -> replicated batch

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(batch, seq_axis)),
        out_specs=P(batch, seq_axis),
        check_vma=False,
    )
    def forward(params, tokens):
        Tl = tokens.shape[1]
        offset = jax.lax.axis_index(seq_axis) * Tl
        return model.apply(params, tokens, positions=offset + jnp.arange(Tl))

    return forward


def make_sp_train_step(config: LlamaConfig, mesh, optimizer,
                       seq_axis: str = "seq", data_axis: str | None = None,
                       donate: bool = False):
    """Jitted ``step(params, opt_state, tokens) -> (params, opt_state, loss)``
    training over sequence-sharded activations (optionally batch-sharded too:
    hybrid DP x SP).  The causal next-token shift in the loss crosses shard
    boundaries; it runs on the global logits so GSPMD inserts the halo
    exchange."""
    forward = make_sp_forward(config, mesh, seq_axis, data_axis)

    def loss_fn(params, tokens):
        return causal_lm_loss(forward(params, tokens), tokens)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def sp_data_sharding(mesh, seq_axis: str = "seq",
                     data_axis: str | None = None) -> NamedSharding:
    """Sharding for the (B, T) token batch consumed by the SP step."""
    return NamedSharding(mesh, P(data_axis, seq_axis))
