"""Byzantine attack models.

Two kinds, matching the north-star requirement (BASELINE.json configs[4]:
"label-flip + Gaussian Byzantine vs Krum/trimmed-mean at 256 clients"):

- **update attacks** transform a malicious client's outgoing update; they plug
  into ``make_fl_round``'s ``attack=``/``malicious_mask=`` arguments and run
  inside the jitted round (signature ``attack(update, params, key) ->
  update``).
- **data attacks** poison a malicious client's local dataset before training;
  they transform the stacked ``ClientDatasets`` up front (label flipping).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..data.split import ClientDatasets

# domain-separation tag for the in-round Byzantine membership draw, same
# discipline as resilience/faults.py's fault-kind tags
_TAG_BYZ = 0xB42


def byzantine_round_mask(seed: int, round_idx, nr: int, fraction: float):
    """Seeded per-round Byzantine membership: each of the ``nr`` cohort
    positions independently turns malicious with probability ``fraction``
    this round.  A pure function of ``(seed, round_idx)`` built from the
    same fold_in chain as ``resilience.FaultPlan.round_masks`` — it traces
    inside the jitted round AND replays eagerly on the host, which is what
    keeps the ``fl_byzantine_clients_total`` counter exact.  Drawn
    cohort-globally so the streaming ``client_chunk`` paths slice it and
    see the identical coalition as the stacked path."""
    if fraction <= 0.0:
        return jnp.zeros((nr,), jnp.bool_)
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), _TAG_BYZ), round_idx
    )
    return jax.random.uniform(key, (nr,)) < fraction


def make_gaussian_attack(sigma: float = 1.0):
    """Replace the update with pure Gaussian noise of scale ``sigma``."""

    def attack(update, params, key):
        leaves, treedef = jax.tree.flatten(update)
        keys = jax.random.split(key, len(leaves))
        noisy = [
            sigma * jax.random.normal(k, leaf.shape, leaf.dtype)
            for k, leaf in zip(keys, leaves)
        ]
        return jax.tree.unflatten(treedef, noisy)

    return attack


def make_sign_flip_attack(scale: float = 1.0):
    """Send the negated (optionally scaled) honest update."""

    def attack(update, params, key):
        return jax.tree.map(lambda u: -scale * u, update)

    return attack


def flip_labels(
    data: ClientDatasets, malicious: np.ndarray, nr_classes: int
) -> ClientDatasets:
    """Label-flip data poisoning: malicious clients relabel every sample
    ``y -> (nr_classes - 1) - y`` (the canonical flip for MNIST/CIFAR).

    ``malicious`` is a boolean (N,) mask over clients.
    """
    malicious = np.asarray(malicious, dtype=bool)
    y = np.array(data.y)
    flipped = (nr_classes - 1) - y
    y[malicious] = flipped[malicious]
    return dataclasses.replace(data, y=y)


def make_alie_attack(z: float = 1.5):
    """ALIE — "A Little Is Enough" (Baruch et al. 2019, public): colluding
    attackers estimate the coordinate-wise mean/std of their own honest
    updates and all submit ``mu + z * sigma`` — a perturbation small
    enough to sit inside the benign spread (defeating distance-based
    defenses like Krum for suitable ``z``) yet consistently biased.

    Collusive: the engine detects ``attack.collusive`` and calls
    ``attack(stacked_updates, malicious_mask, params, key)`` ONCE with the
    whole stack instead of vmapping per client — attackers need shared
    statistics.  ``z`` trades stealth vs damage; the paper derives a
    z_max from (n, f) via the normal quantile, left to the caller.
    """

    def attack(stacked, mal_mask, params, key):
        w = mal_mask.astype(jnp.float32)
        nm = jnp.maximum(jnp.sum(w), 1.0)

        def per_leaf(leaf):
            wm = w.reshape((-1,) + (1,) * (leaf.ndim - 1))
            mu = jnp.sum(leaf * wm, axis=0) / nm
            var = jnp.sum(jnp.square(leaf - mu) * wm, axis=0) / nm
            adv = (mu + z * jnp.sqrt(var + 1e-12)).astype(leaf.dtype)
            return jnp.where(wm > 0, adv[None], leaf)

        return jax.tree.map(per_leaf, stacked)

    attack.collusive = True
    return attack
