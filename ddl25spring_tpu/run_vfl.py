"""CLI runner for vertical-FL experiments (the tutorial_2b family).

    python -m ddl25spring_tpu.run_vfl --mode classify --nr-clients 4
    python -m ddl25spring_tpu.run_vfl --mode vae --epochs 1000

``classify`` trains the split-NN (per-party bottom models, server top —
lab/tutorial_2b/vfl.py) on heart.csv and reports test accuracy; ``vae``
trains the split VFL-VAE (per-party encoders/decoders, server VAE over the
concatenated latent — lab/tutorial_2b/exercise_3.py) and reports the
combined-loss trajectory.  ``--nr-clients`` reproduces the exercise-2
client-scaling grid point; ``--permutation-seed`` the exercise-1 feature
permutations.  heart.csv loads real from the reference mount, so accuracies
are directly comparable to the homework-2 outputs (BASELINE.md).
"""

from __future__ import annotations

import numpy as np

from .configs import VflConfig, parse_config
from .utils import MetricsLogger


def _partitions(cfg: VflConfig):
    from .data import load_heart_classification, load_heart_df
    from .data.heart import CATEGORICAL
    from .vfl.splitnn import partition_features

    df, _ = load_heart_df()
    d = load_heart_classification()
    raw = [c for c in df.columns if c != "target"]
    perm = (
        None if cfg.permutation_seed < 0
        else np.random.default_rng(cfg.permutation_seed).permutation(len(raw))
    )
    parts = partition_features(raw, d.feature_names, CATEGORICAL,
                               cfg.nr_clients, permutation=perm)
    idx = {n: i for i, n in enumerate(d.feature_names)}
    slices = [np.array([idx[c] for c in cols]) for cols in parts]
    return d, slices


def run(cfg: VflConfig):
    from .vfl import VFLNetwork, VFLVAE

    d, slices = _partitions(cfg)
    logger = MetricsLogger(cfg.metrics_path) if cfg.metrics_path else None
    log = (
        (lambda epoch, loss: logger.log("epoch", idx=epoch, loss=loss))
        if logger else None
    )

    try:
        if cfg.mode == "classify":
            y1h = np.eye(2, dtype=np.float32)[d.y]
            split = int(0.8 * len(d.y))
            if cfg.sharded:
                import jax

                from .parallel import make_mesh
                from .vfl import PartyShardedVFL

                # party-axis size: largest divisor of the party count that
                # fits the devices (parties fold onto devices in equal
                # groups; make_mesh happily uses a device subset)
                nd = len(jax.devices())
                axis = max(d for d in range(1, nd + 1)
                           if cfg.nr_clients % d == 0)
                mesh = make_mesh({"party": axis}) if axis > 1 else None
                if mesh is None:
                    print(f"note: cannot split {cfg.nr_clients} parties "
                          f"across {nd} device(s); running unsharded")
                net = PartyShardedVFL(
                    feature_slices=slices,
                    out_dim=2 * max(len(s) for s in slices),
                    seed=cfg.seed, mesh=mesh,
                )
            else:
                net = VFLNetwork(feature_slices=slices,
                                 outs_per_party=[2 * len(s) for s in slices],
                                 seed=cfg.seed)
            history = net.train_with_settings(
                cfg.epochs, cfg.batch_size, d.x[:split], y1h[:split],
                log_loss=log,
            )
            acc, loss = net.test(d.x[split:], y1h[split:])
            print(f"{cfg.nr_clients} clients: test acc {acc * 100:.2f}% "
                  f"(test loss {loss:.4f})")
            curves = {f"{cfg.nr_clients} clients": history}
            result = acc
        elif cfg.mode == "vae":
            x_clients = [d.x[:, s] for s in slices]
            vae = VFLVAE(feature_slices=slices, seed=cfg.seed)
            history = vae.train(x_clients, epochs=cfg.epochs)
            if logger:
                for e, l in enumerate(history):
                    logger.log("epoch", idx=e, loss=l)
            print(f"combined loss: {history[0]:.0f} -> {history[-1]:.0f} "
                  f"({len(history)} epochs)")
            curves = {"VFL-VAE combined": history}
            result = history[-1]
        else:
            raise ValueError(f"unknown mode {cfg.mode!r}")
    finally:
        if logger:
            logger.close()

    if cfg.plot_dir:
        from pathlib import Path

        from .utils import plot_loss_curves

        out = plot_loss_curves(
            curves, Path(cfg.plot_dir) / f"vfl_{cfg.mode}_loss.png",
            title=f"VFL {cfg.mode} training loss "
                  f"({cfg.nr_clients} parties)",
            logy=cfg.mode == "vae",
        )
        print(f"wrote {out}")
    return result


def main(argv=None):
    from .utils.platform import select_platform

    select_platform()
    return run(parse_config(VflConfig, argv))


if __name__ == "__main__":
    main()
