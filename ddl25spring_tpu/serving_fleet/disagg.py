"""Disaggregated prefill: admit-side prefill runs in a dedicated
worker, decode replicas install finished pages without stalling.

The paged admit program (``serving._paged_programs``) is one fused
dispatch: vmapped right-aligned prefill + page copies + tokens/pos/pad
scatter.  Disaggregation splits it at its natural seam:

- **prefill** (worker side, at ``submit`` time, while the request still
  waits in the queue): the SAME ``_right_aligned_prefill`` math writes
  the prompt's KV into pool pages the worker allocated, and the pages
  are handed to the decode side through the shared
  :class:`~ddl25spring_tpu.models.kv_pool.PrefixRegistry` (the registry
  holds the base reference until the slot acquires ownership — the same
  refcount discipline shared system prompts use).
- **install** (decode side, at admission): a scatter of the staged first
  tokens / pads into the scheduler vectors.  No prefill work happens on
  the decode replica's critical path — a long prompt costs the decode
  loop one ``.at[].set`` dispatch instead of a full forward.

Bit-identity with colocated mode is structural: prefill rows are
vmapped and row-independent (the group shape cannot change a row's
math — the same property ``serve_fused`` vs the batcher already
relies on), the page contents are written by the same
``dynamic_update_slice`` slices, and decode reads them through the same
block tables.  Only the PHYSICAL page numbers differ (allocation order
moves from admission time to submit time); streams never see them.

Staging is bounded by a deadlock guard: the worker never takes prompt
pages the FIFO head's decode tail will need (staged pages are pinned
until admission, so unguarded staging could wedge head-of-line
admission on a small pool).  A request the guard skips simply falls
back to the colocated fused admit — same tokens, one fused dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.llama import Llama
from ..models.serving import ContinuousBatcher, _right_aligned_prefill

__all__ = ["DisaggregatedBatcher", "PrefillWorker"]


@functools.lru_cache(maxsize=8)
def _prefill_programs(config, prefill_width: int, prefix_len: int,
                      kv_page: int):
    """The split admit pair: ``prefill`` (worker) + ``install`` (decode
    replica).  Cached like ``serving._programs`` — same-shape workers
    across a fleet share one compiled set."""
    cfg = dataclasses.replace(config, decode=True)
    model = Llama(cfg)
    W = prefill_width
    P = prefix_len
    lo = P // kv_page

    @jax.jit
    def prefill(params, pool, rows, lengths, copy_dst, prefix_cache=None):
        """The admit program's first half: vmapped prefill of the (G, W)
        prompt block and the static G x n_copy page copies into the
        pool (``serving._paged_programs.admit`` minus the scheduler
        scatter)."""
        row_caches, firsts, pads = jax.vmap(
            functools.partial(_right_aligned_prefill, model, W, P),
            in_axes=(None, 0, 0, None),
        )(params, rows, lengths, prefix_cache)
        for g in range(rows.shape[0]):
            for c in range(copy_dst.shape[1]):
                start = (lo + c) * kv_page
                pool = jax.tree.map(
                    lambda big, rc: jax.lax.dynamic_update_slice(
                        big,
                        rc[g][:, start:start + kv_page].astype(big.dtype),
                        (copy_dst[g, c],) + (0,) * (big.ndim - 1),
                    ),
                    pool, row_caches,
                )
        return pool, firsts, pads

    @jax.jit
    def install(tokens, pos, pad, slots, firsts, pads):
        """The admit program's second half: scheduler-vector scatter
        (pad lanes repeat a real admission — idempotent)."""
        return (tokens.at[slots].set(firsts),
                pos.at[slots].set(P + W),
                pad.at[slots].set(pads))

    return prefill, install


class PrefillWorker:
    """Admit-side prefill bound to one paged decode replica.

    Shares the replica's pool, registry, params and cache tree — on a
    disaggregated deployment this is the prefill process's view of the
    shared KV store; here it is the same host object, which is what
    makes colocated-vs-disaggregated bit-identity testable.  Handoff
    keys are ``(-1, seq) + prompt`` — the ``-1`` sentinel keeps them
    disjoint from real token prefixes in the shared registry (token ids
    are non-negative), ``seq`` disambiguates duplicate prompts."""

    def __init__(self, batcher):
        if not getattr(batcher, "_paged", False):
            raise ValueError(
                "disaggregated prefill needs kv_layout='paged' (the "
                "page pool IS the handoff medium)")
        self.batcher = batcher
        self._prefill, self._install = _prefill_programs(
            batcher.config, batcher.prefill_width, batcher.prefix_len,
            batcher.kv_page)
        self._staged: dict = {}  # rid -> (key, firsts (1,), pads (1,))
        self._tails: dict = {}   # rid -> decode-tail pages still needed
        self._seq = 0
        self.stats = {"prefilled": 0, "skipped": 0}

    def _key(self, seq: int, prompt) -> tuple:
        return (-1, seq) + tuple(int(t) for t in prompt)

    def staged(self, rid) -> bool:
        return rid in self._staged

    def tail_of(self, rid) -> int:
        return self._tails[rid]

    def stage(self, rid, prompt, budget: int) -> bool:
        """Prefill ``prompt`` into freshly allocated pool pages and
        register them for handoff; False when the deadlock guard or an
        empty pool skips it (the request admits colocated instead)."""
        b = self.batcher
        n_copy = b._n_copy
        tail = b._pages_needed(budget) - n_copy
        pool = b._pool
        # the FIFO head's decode tail must stay allocatable after this
        # staging pins n_copy more pages, else admission wedges
        worst_tail = max(list(self._tails.values()) + [tail])
        if pool.free_pages - n_copy < worst_tail:
            self.stats["skipped"] += 1
            return False
        pages = pool.alloc(n_copy)
        if pages is None:
            self.stats["skipped"] += 1
            return False
        W = b.prefill_width
        rows = np.zeros((1, W), np.int32)
        rows[0, :len(prompt)] = prompt
        lengths = np.asarray([len(prompt)], np.int32)
        copy_dst = np.asarray([pages], np.int32)
        t0 = time.perf_counter()
        with obs.span("serving.prefill_offload", tokens=len(prompt)):
            b.cache, firsts, pads = self._prefill(
                b.params, b.cache, jnp.asarray(rows),
                jnp.asarray(lengths), jnp.asarray(copy_dst),
                b._prefix_cache)
        rt = obs.reqtrace()
        if rt is not None:
            rt.note(rid, "prefill",
                    replica=getattr(b, "_replica_ix", None),
                    seconds=time.perf_counter() - t0,
                    tokens=len(prompt))
        key = self._key(self._seq, prompt)
        self._seq += 1
        b._registry.put(key, pages)  # registry takes the base reference
        self._staged[rid] = (key, firsts, pads)
        self._tails[rid] = tail
        self.stats["prefilled"] += 1
        obs.inc("serving_prefill_offloaded_total")
        return True

    def collect(self, rid):
        """Admission-side handoff: ownership of the prefilled pages
        moves from the registry to the admitting slot (acquire adds the
        occupant reference, drop releases the registry's base one)."""
        key, firsts, pads = self._staged.pop(rid)
        self._tails.pop(rid)
        b = self.batcher
        pages = b._registry.acquire(key)
        b._registry.drop(key)
        return pages, firsts, pads


class DisaggregatedBatcher(ContinuousBatcher):
    """Paged batcher whose streaming admissions prefill in a
    :class:`PrefillWorker` at ``submit`` time.

    ``prefill_mode="colocated"`` disables the worker entirely — the
    exact base batcher, which the bit-identity tests compare against.
    ``run()`` (workload known up front) always takes the colocated
    fused path; disaggregation pays off when requests ARRIVE over time
    and prefill can overlap queue wait.
    """

    def __init__(self, config, params, *,
                 prefill_mode: str = "disaggregated", **kwargs):
        if prefill_mode not in ("disaggregated", "colocated"):
            raise ValueError(
                f"prefill_mode must be 'disaggregated' or 'colocated', "
                f"got {prefill_mode!r}")
        kwargs.setdefault("kv_layout", "paged")
        super().__init__(config, params, **kwargs)
        self.prefill_mode = prefill_mode
        self.prefill_worker = (PrefillWorker(self)
                               if prefill_mode == "disaggregated" else None)

    def submit(self, rid, prompt, max_new_tokens: int,
               deadline_s: float | None = None) -> None:
        super().submit(rid, prompt, max_new_tokens,
                       deadline_s=deadline_s)
        w = self.prefill_worker
        if (w is not None and int(max_new_tokens) > 0
                and self._queue and self._queue[-1][0] == rid):
            # the queue entry carries the STRIPPED prompt the compiled
            # programs expect
            w.stage(rid, self._queue[-1][1], self._queue[-1][2])

    def _admit_from(self, pending: list) -> list:
        """Base head-of-line admission, but a staged request's prompt
        pages are already held — only its decode tail counts against the
        free-page budget."""
        w = self.prefill_worker
        if w is None:
            return super()._admit_from(pending)
        free = [s for s, sl in enumerate(self.slots)
                if sl.free and s not in self._quarantined]
        group = []
        avail = self._pool.free_pages
        while pending and free:
            # queue entries grew an adapter_id field; the disagg replica
            # has no adapter pool, so only the first three matter here
            rid, _prompt, budget = pending[0][:3]
            need = (w.tail_of(rid) if w.staged(rid)
                    else self._pages_needed(budget))
            if need > avail:
                break
            avail -= need
            pending.pop(0)
            group.append((free.pop(0), rid, _prompt, budget))
        return group

    def _admit_group(self, admissions):
        w = self.prefill_worker
        if w is None:
            return super()._admit_group(admissions)
        staged = [a for a in admissions if w.staged(a[1])]
        rest = [a for a in admissions if not w.staged(a[1])]
        if not staged:
            return super()._admit_group(admissions)
        if not rest:
            return self._admit_staged(staged)
        # mixed group: each sub-path books its own slots; the composed
        # return only feeds _sync_admit_bookkeep's host fetch, in the
        # caller's admission order
        firsts = np.zeros((len(admissions),), np.int64)
        pos_of = {rid: i for i, (_s, rid, _p, _b) in
                  enumerate(admissions)}
        sub = np.asarray(super()._admit_group(rest))
        for j, (_s, rid, _p, _b) in enumerate(rest):
            firsts[pos_of[rid]] = int(sub[j])
        sub = np.asarray(self._admit_staged(staged))
        for j, (_s, rid, _p, _b) in enumerate(staged):
            firsts[pos_of[rid]] = int(sub[j])
        return firsts

    def _admit_staged(self, admissions):
        """Admit a group whose prefill already ran: allocate decode
        tails, wire block tables to the handed-off pages, and install
        the staged first tokens in one scatter dispatch — no model
        forward on the decode path."""
        G0 = len(admissions)
        self._obs_admitted(admissions)
        G = 1 << (G0 - 1).bit_length()
        w = self.prefill_worker
        hp = self._head_len
        slot_ix = np.zeros((G,), np.int32)
        firsts_rows = []
        pads_rows = []
        for g, (s, rid, _prompt, _budget) in enumerate(admissions):
            tail_need = w.tail_of(rid)
            pages, firsts_g, pads_g = w.collect(rid)
            tail = self._pool.alloc(tail_need) if tail_need else []
            if tail is None:
                raise RuntimeError("KV pool exhausted mid-group")
            if self._head_pages:
                if self._prefix_tokens is not None:
                    self._registry.acquire(self._prefix_tokens)
                else:
                    self._pool.share(self._head_pages)
                self._tables[s, :hp] = self._head_pages
            allp = pages + tail
            self._tables[s, hp:hp + len(allp)] = allp
            self._tables[s, hp + len(allp):] = 0
            slot_ix[g] = s
            firsts_rows.append(firsts_g)
            pads_rows.append(pads_g)
            self._hit_rids.discard(rid)
        slot_ix[G0:] = slot_ix[G0 - 1]
        firsts = jnp.concatenate(
            firsts_rows + [firsts_rows[-1]] * (G - G0))
        pads = jnp.concatenate(pads_rows + [pads_rows[-1]] * (G - G0))
        if self.prefix_len:
            self.stats["prefix_hits"] += G0
            self.stats["prefix_hit_tokens"] += G0 * self.prefix_len
            obs.inc("serving_prefix_hits_total", G0)
            obs.inc("serving_prefix_hit_tokens_total",
                    G0 * self.prefix_len)
        with obs.span("serving.admit", group=G0, disaggregated=True):
            self.tokens, self.pos, self.pad = self._install_fn(
                self.tokens, self.pos, self.pad, jnp.asarray(slot_ix),
                firsts, pads)
            if obs.enabled():
                obs.set_gauge("serving_kv_pages_in_use",
                              self._pool.pages_in_use)
        now = (time.perf_counter()
               if self._deadlines or self.fault_plan is not None else 0.0)
        for g, (s, rid, _prompt, budget) in enumerate(admissions):
            sl = self.slots[s]
            sl.request_id = rid
            sl.emitted = [(firsts, g, 1)]
            sl.budget = budget - 1
            sl.total = budget
            sl.done_eos = False
            sl.ok_refs = []
            rel = self._deadlines.get(rid)
            if (self.fault_plan is not None
                    and self.fault_plan.serving_fault(rid)):
                sl.deadline = now
            else:
                sl.deadline = None if rel is None else now + rel
        self.stats["admitted"] += G0
        return firsts

    @property
    def _install_fn(self):
        return self.prefill_worker._install
