"""Hardware-independent north-star tracking on the CPU backend.

The real north star (bench.py: 256 clients, CIFAR-10, ResNet-18, one real
TPU) needs the tunnel, which has been down for whole rounds (BENCH_r01-r03
all "device unreachable").  This tool measures two SCALED but
architecturally faithful variants of the same engine every round and
appends them to ``results/northstar_cpu_trend.jsonl``:

- ``resnet-1dev``: 32 clients, C=0.25 (8 sampled), ResNet-18 f32, B=50,
  E=1, single CPU device.  Tracks the model+engine compute path.  Its
  XLA:CPU compile is minutes-long the FIRST time (the conv program — the
  8-device-mesh variant of this config never finished compiling in 36
  minutes, which is why the mesh leg uses the CNN below); the persistent
  compile cache makes later rounds take seconds.
- ``cnn-mesh8``: the same FL round machinery (vmap over sampled clients +
  weighted-mean aggregation + with_sharding_constraint) with the MNIST CNN
  over the 8-device virtual CPU mesh.  Compiles in seconds and tracks the
  SHARDED engine path — the part of the north star the ResNet leg can't
  afford to cover on CPU.

FL-engine perf regressions then show up as a dropped rounds/sec in the
committed trend even when the TPU is dark
(``tests/test_northstar_trend.py`` gates on it).

Usage: python tools/northstar_cpu.py [--rounds N] [--dry-run]
           [--variant resnet-1dev|cnn-mesh8|all]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from ddl25spring_tpu.utils.platform import select_platform  # noqa: E402

select_platform("cpu")  # explicit arg: DDL25_PLATFORM must not override the
#                         CPU pin; we want only the persistent compile cache

TREND = Path(__file__).resolve().parent.parent / "results" / "northstar_cpu_trend.jsonl"


def _measure_rounds(server, nr_rounds: int):
    """Compile (warmup round) + time ``nr_rounds`` unfused dispatches.

    Unfused on purpose: CPU dispatch overhead is negligible, and the fused
    fori_loop program would force a SECOND multi-minute XLA:CPU compile of
    the same round body."""
    t0 = time.perf_counter()
    params = server.round_fn(server.params, server.run_key, 0)
    jax.block_until_ready(params)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in range(1, nr_rounds + 1):
        params = server.round_fn(params, server.run_key, r)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    return nr_rounds / dt, compile_s


def _resnet_1dev(seed: int = 10):
    import jax.numpy as jnp

    from ddl25spring_tpu.data.cifar import cifar_input_transform
    from ddl25spring_tpu.data.synth_device import device_synthetic_clients
    from ddl25spring_tpu.fl import FedAvgServer
    from ddl25spring_tpu.fl.task import classification_task
    from ddl25spring_tpu.models import ResNet18

    client_data, test_x, test_y = device_synthetic_clients(
        nr_clients=32, n_train=6400, n_test=1000, seed=seed, pad_multiple=50,
    )
    # f32: CPU bf16 is software-emulated (a bf16 warmup round ran >45 min)
    task = classification_task(
        ResNet18(dtype=jnp.float32), (32, 32, 3), test_x, test_y,
        input_transform=cifar_input_transform(jnp.float32),
    )
    return FedAvgServer(task, lr=0.05, batch_size=50, client_data=client_data,
                        client_fraction=0.25, nr_local_epochs=1, seed=seed)


def _cnn_mesh8(seed: int = 10):
    import numpy as np

    from ddl25spring_tpu.data import load_mnist, split_dataset
    from ddl25spring_tpu.fl import FedAvgServer
    from ddl25spring_tpu.fl.task import mnist_task
    from ddl25spring_tpu.parallel import make_mesh

    ds = load_mnist(n_train=4096, n_test=512)
    task = mnist_task(ds.test_x, ds.test_y)
    data = split_dataset(ds.train_x, ds.train_y, 32, True, seed=seed,
                         pad_multiple=32)
    mesh = make_mesh({"clients": len(jax.devices())})
    return FedAvgServer(task, lr=0.05, batch_size=32, client_data=data,
                        client_fraction=0.25, nr_local_epochs=1, seed=seed,
                        mesh=mesh)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--variant", default="all",
                    choices=["resnet-1dev", "cnn-mesh8", "all"])
    ap.add_argument("--dry-run", action="store_true",
                    help="measure but do not append to the trend file")
    args = ap.parse_args()

    assert len(jax.devices()) == 8, jax.devices()
    rev = "unknown"
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=TREND.parent.parent,
        ).stdout.strip() or "unknown"
    except OSError:
        pass

    backends = {"resnet-1dev": "cpu-1dev", "cnn-mesh8": "cpu-mesh8"}
    builders = {"resnet-1dev": _resnet_1dev, "cnn-mesh8": _cnn_mesh8}
    names = list(builders) if args.variant == "all" else [args.variant]
    for name in names:
        server = builders[name]()
        rps, compile_s = _measure_rounds(server, args.rounds)
        entry = {
            "date": time.strftime("%Y-%m-%d"),
            "git": rev,
            "variant": name,
            "rounds_per_sec": round(rps, 4),
            "rounds_timed": args.rounds,
            "compile_s": round(compile_s, 1),
            "backend": backends[name],
        }
        print(json.dumps(entry), flush=True)
        if not args.dry_run:
            with TREND.open("a") as f:
                f.write(json.dumps(entry) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
