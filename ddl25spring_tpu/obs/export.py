"""Chrome-trace / Perfetto export of span JSONL files.

Stdlib-only (covered by the jax-import-free guard).  Takes one or many
telemetry JSONL files — FL server, spawned client/eval subprocesses,
multihost ranks — and merges their ``span`` events into a single
Chrome-trace JSON (the ``{"traceEvents": [...]}`` dialect that both
``chrome://tracing`` and https://ui.perfetto.dev load):

* one *process track* (pid) per distinct ``(file, process_index)`` pair,
  named after the rank and source file, so multi-rank merges keep events
  on distinct tracks even when every rank reports ``process == 0``;
* one *thread track* (tid) per recording thread within a file;
* ``X`` complete events (start + duration in µs) — duration is the fenced
  ``device_seconds`` when present (it encloses the dispatch wall time),
  else wall ``seconds``;
* ``s``/``f`` flow events stitching cross-process parent links
  (``parent_id`` recorded in another file) so the UI draws the arrow from
  the server's round span into the child's root span.

Span start comes from the ``start_ts`` field (perf_counter anchored to
the wall clock once per process — see ``obs/trace.py``), falling back to
``ts - seconds`` for pre-tracing JSONL.
"""

from __future__ import annotations

import json
from pathlib import Path

__all__ = ["load_span_events", "chrome_trace", "write_chrome_trace",
           "validate"]


def load_span_events(paths) -> list[dict]:
    """``span`` events from one or many JSONL files, each tagged with the
    0-based ``_file`` index and ``_src`` stem of its origin."""
    events = []
    for i, path in enumerate(paths):
        p = Path(path)
        with p.open() as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("event") != "span":
                    continue
                rec["_file"] = i
                rec["_src"] = p.stem
                events.append(rec)
    return events


def _start_of(e) -> float | None:
    if "start_ts" in e:
        return float(e["start_ts"])
    if "ts" in e and "seconds" in e:
        return float(e["ts"]) - float(e["seconds"])
    return None


def _duration_of(e) -> float:
    return float(e.get("device_seconds", e.get("seconds", 0.0)))


_ID_KEYS = ("trace_id", "span_id", "parent_id", "parent", "process")
_SKIP_KEYS = set(_ID_KEYS) | {
    "name", "seconds", "device_seconds", "depth", "start_ts", "ts",
    "event", "_file", "_src",
}


def chrome_trace(events_or_paths) -> dict:
    """Merge span events (or JSONL paths) into a Chrome-trace dict."""
    if events_or_paths and not isinstance(events_or_paths[0], dict):
        events = load_span_events(events_or_paths)
    else:
        events = list(events_or_paths)

    starts = [s for e in events if (s := _start_of(e)) is not None]
    t0 = min(starts) if starts else 0.0

    pids: dict = {}      # (file, process) -> pid
    tids: dict = {}      # (pid, thread-name) -> tid
    trace_events = []
    span_pid = {}        # span_id -> (pid, start_us, end_us) for flows

    def _pid(e) -> int:
        key = (e.get("_file", 0), e.get("process", 0))
        if key not in pids:
            pid = len(pids)
            pids[key] = pid
            label = f"rank{key[1]}"
            if e.get("_src"):
                label += f" · {e['_src']}"
            trace_events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": label},
            })
            trace_events.append({
                "name": "process_sort_index", "ph": "M", "pid": pid,
                "tid": 0, "args": {"sort_index": pid},
            })
        return pids[key]

    def _tid(pid: int, e) -> int:
        thread = e.get("thread", "MainThread")
        key = (pid, thread)
        if key not in tids:
            tid = sum(1 for (p, _n) in tids if p == pid)
            tids[key] = tid
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": thread},
            })
        return tids[key]

    for e in events:
        start = _start_of(e)
        if start is None or "name" not in e:
            continue
        pid = _pid(e)
        tid = _tid(pid, e)
        ts_us = (start - t0) * 1e6
        dur_us = max(_duration_of(e), 0.0) * 1e6
        args = {k: e[k] for k in _ID_KEYS if k in e}
        args.update({k: v for k, v in e.items() if k not in _SKIP_KEYS})
        trace_events.append({
            "name": e["name"], "ph": "X", "cat": "span",
            "pid": pid, "tid": tid,
            "ts": round(ts_us, 3), "dur": round(dur_us, 3),
            "args": args,
        })
        if e.get("span_id"):
            span_pid[e["span_id"]] = (pid, tid, ts_us, ts_us + dur_us)

    # flow arrows for parent links that cross a process/file boundary
    flow = 0
    for e in events:
        parent = e.get("parent_id")
        child = e.get("span_id")
        if not parent or not child:
            continue
        src = span_pid.get(parent)
        dst = span_pid.get(child)
        if src is None or dst is None or src[0] == dst[0]:
            continue
        flow += 1
        bind = min(max(dst[2], src[2]), src[3])  # inside the source slice
        trace_events.append({
            "name": "trace", "cat": "flow", "ph": "s", "id": flow,
            "pid": src[0], "tid": src[1], "ts": round(bind, 3)})
        trace_events.append({
            "name": "trace", "cat": "flow", "ph": "f", "bp": "e",
            "id": flow, "pid": dst[0], "tid": dst[1],
            "ts": round(dst[2], 3)})

    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "ddl25spring_tpu.obs.export",
            "epoch_offset_s": t0,
            "files": len({e.get("_file", 0) for e in events}),
        },
    }


def write_chrome_trace(paths, out_path) -> dict:
    """Export JSONL files to a Chrome-trace JSON on disk; returns the
    trace dict."""
    trace = chrome_trace(list(paths))
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(trace))
    return trace


def validate(trace: dict, eps_us: float = 50.0) -> list[str]:
    """Structural checks on an exported trace; returns problems (empty ==
    valid).  Checks the Chrome-trace shape, that ``X`` events on each
    (pid, tid) track nest properly (stack discipline), and that
    parent/child id links stay within one trace_id."""
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    xs = [e for e in events if e.get("ph") == "X"]
    if not xs:
        problems.append("no X events")
    by_track: dict = {}
    span_trace = {}
    for e in xs:
        for key in ("name", "pid", "tid", "ts", "dur"):
            if key not in e:
                problems.append(f"X event missing {key}: {e}")
                break
        else:
            by_track.setdefault((e["pid"], e["tid"]), []).append(e)
            sid = e.get("args", {}).get("span_id")
            if sid:
                span_trace[sid] = e.get("args", {}).get("trace_id")
    for (pid, tid), track in by_track.items():
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # end timestamps
        for e in track:
            start, end = e["ts"], e["ts"] + e["dur"]
            while stack and stack[-1] <= start + eps_us:
                stack.pop()
            if stack and end > stack[-1] + eps_us:
                problems.append(
                    f"overlap on track ({pid},{tid}): {e['name']} ends "
                    f"{end - stack[-1]:.1f}us after its enclosing span")
            stack.append(end)
    for e in xs:
        args = e.get("args", {})
        parent = args.get("parent_id")
        if parent and parent in span_trace:
            if span_trace[parent] != args.get("trace_id"):
                problems.append(
                    f"{e['name']}: parent {parent} in different trace")
    return problems
