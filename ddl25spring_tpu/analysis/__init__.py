"""graftlint — static contracts for a TPU-native codebase.

Five ``ast``-level passes over the tree (no code under analysis is ever
imported, and this package itself never imports jax):

- **import-purity** (``IMP*``) — the ``manifest.HOST_ONLY_MODULES``
  closure must not reach a top-level ``import jax``;
- **trace-hygiene** (``TRC*``) — functions reachable from
  jit/pallas_call/shard_map must not branch on tracers, concretize
  (``.item()``/``float()``), call ``np.*`` on traced values, ``print``,
  or read clocks/RNGs at trace time; ``lax.ppermute`` inside a
  ``shard_map`` body must name an axis the call site's literal specs
  mention (``TRC008``);
- **determinism** (``DET*``) — no unseeded global RNG state, no
  wall-clock-derived seeds or identifiers;
- **donation-safety** (``DON*``) — no reads of a donated buffer after
  the donating jitted call;
- **metric-drift** (``MET*``) — code, ``tools/obs_report.py`` and
  ``docs/OBSERVABILITY.md`` must agree on every metric name and kind.

CLI: ``python tools/graftlint.py [paths] [--json] [--baseline FILE]``.
Accepted violations live in ``tools/graftlint_baseline.json``, each with
a justification; ``tests/test_analysis.py`` keeps the shipped tree at
zero non-baselined findings.  See ``docs/ANALYSIS.md``.
"""

from __future__ import annotations

from pathlib import Path

from .core import (  # noqa: F401  (re-exported API)
    PASS_ORDER,
    BaselineError,
    Finding,
    ProjectIndex,
    assign_ids,
    collect_paths,
    load_baseline,
    render_baseline,
)

__all__ = [
    "PASS_ORDER", "BaselineError", "Finding", "ProjectIndex",
    "assign_ids", "collect_paths", "load_baseline", "render_baseline",
    "run_passes",
]


def _pass_modules():
    from . import determinism, donation, hygiene, imports, metrics_drift
    return {
        imports.PASS_ID: imports,
        hygiene.PASS_ID: hygiene,
        determinism.PASS_ID: determinism,
        donation.PASS_ID: donation,
        metrics_drift.PASS_ID: metrics_drift,
    }


def run_passes(paths: list[Path], repo_root: Path,
               passes: tuple[str, ...] | None = None) -> list["Finding"]:
    """Run the selected passes (default: all, in ``PASS_ORDER``) over
    ``paths`` and return findings with stable IDs assigned."""
    mods = _pass_modules()
    selected = passes or PASS_ORDER
    unknown = [p for p in selected if p not in mods]
    if unknown:
        raise ValueError(f"unknown pass(es): {', '.join(unknown)} "
                         f"(known: {', '.join(PASS_ORDER)})")
    idx = collect_paths(paths, repo_root)
    findings: list[Finding] = []
    for pid in PASS_ORDER:
        if pid in selected:
            findings.extend(mods[pid].run(idx))
    assign_ids(findings)
    return findings
