"""Communication-compressed data parallelism.

The reference ships every full-precision gradient through its all-reduce
(intro_DP_GA.py:55-63 flattens ALL grads into one fp32 vector before
``all_reduce``); it has no compression of any kind.  This module adds the two
standard gradient-compression families as drop-in DP trainers, both expressed
as pure jit transforms so the whole round stays one SPMD program:

- **top-k sparsification with error feedback** (Deep Gradient Compression,
  Lin et al., ICLR 2018): each shard keeps only the largest-magnitude k
  fraction of its gradient, accumulates what it dropped into a residual, and
  adds the residual back next step — the residual makes compressed SGD track
  uncompressed SGD instead of silently losing mass.
- **int8 stochastic quantization** (QSGD-style, Alistarh et al., 2017):
  per-tensor symmetric scale, stochastic rounding so the quantizer is
  unbiased in expectation.

A note on what "compression" means on a TPU mesh: the collective still moves
dense arrays (XLA has no sparse all-reduce), so these trainers model the
*algorithm* (what the update loses / how error feedback recovers it) rather
than the wire format.  That is exactly what the correctness oracles need —
and on real multi-host DCN the same transforms feed an 8-bit
``psum`` by casting the quantized values, which IS a wire-format win.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from .compat import shard_map
from jax.sharding import PartitionSpec as P

from .collectives import (instrument_collectives, tree_nr_leaves,
                          tree_payload_bytes)


def topk_sparsify(tree, ratio: float):
    """Keep the largest-magnitude ``ratio`` fraction of entries per leaf
    (at least 1), zero the rest.  Returns (sparse_tree, dropped_tree)."""
    if not 0.0 < ratio <= 1.0:
        raise ValueError(f"ratio must be in (0, 1], got {ratio}")

    def one(leaf):
        flat = leaf.reshape(-1)
        k = max(1, int(ratio * flat.size))
        thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
        mask = (jnp.abs(flat) >= thresh).reshape(leaf.shape)
        sparse = jnp.where(mask, leaf, 0)
        return sparse, leaf - sparse

    pairs = jax.tree.map(one, tree)
    return (jax.tree.map(lambda p: p[0], pairs, is_leaf=lambda x: isinstance(x, tuple)),
            jax.tree.map(lambda p: p[1], pairs, is_leaf=lambda x: isinstance(x, tuple)))


def int8_encode(tree, key):
    """Stochastically round each inexact leaf to int8 on a per-tensor
    symmetric scale (QSGD-style, unbiased).  Returns ``(q_tree, scale_tree)``
    where ``q_tree`` holds int8 leaves and ``scale_tree`` the matching f32
    scalar scales — the STORED form, 1/4 the bytes of an f32 leaf, which is
    what lets the FL engine hold a whole robust-aggregation update stack in
    int8 (``make_fl_round(robust_stack='int8')``).  Non-inexact leaves pass
    through unchanged with a unit scale."""

    def one(leaf, k):
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf, jnp.float32(1.0)
        scale = jnp.maximum(jnp.max(jnp.abs(leaf)), 1e-12) / 127.0
        scaled = leaf / scale
        low = jnp.floor(scaled)
        p_up = scaled - low
        up = jax.random.uniform(k, leaf.shape) < p_up
        q = jnp.clip(low + up, -127, 127).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    leaves, treedef = jax.tree.flatten(tree)
    keys = jax.random.split(key, len(leaves))
    enc = [one(l, k) for l, k in zip(leaves, keys)]
    return (
        jax.tree.unflatten(treedef, [q for q, _ in enc]),
        jax.tree.unflatten(treedef, [s for _, s in enc]),
    )


def int8_decode(q_tree, scale_tree, like=None):
    """Inverse of :func:`int8_encode`: dequantize int8 leaves (pass-through
    leaves come back untouched).  ``like`` is a template pytree supplying
    the output dtype per leaf (e.g. the params the updates were computed
    from); without it, int8 leaves dequantize as ``scale.dtype * q``
    (f32)."""
    if like is None:
        like = scale_tree

    def one(q, s, l):
        if q.dtype != jnp.int8:
            return q
        return q.astype(l.dtype) * s.astype(l.dtype)

    return jax.tree.map(one, q_tree, scale_tree, like)


def quantize_int8(tree, key):
    """Stochastically round each leaf to int8 on a per-tensor symmetric
    scale; returns the dequantized tree (unbiased: E[q(x)] == x).  The
    immediate encode/decode round-trip models the WIRE effect of int8
    uplink compression; callers that want to *store* the compressed form
    (the FL engine's robust-aggregation stack) use :func:`int8_encode` /
    :func:`int8_decode` directly."""
    q, s = int8_encode(tree, key)
    return int8_decode(q, s, like=tree)


def int8_error_bound(absmax, *, stochastic: bool = False):
    """Worst-case per-element dequantization error of the symmetric int8
    scheme used everywhere in this repo (``scale = absmax / 127``): one
    full quantization step ``scale`` under stochastic rounding
    (:func:`int8_encode` — unbiased, so the wire average cancels), half a
    step ``scale / 2`` under round-to-nearest (the serving KV cache,
    models/llama.py ``quant`` — deterministic, so greedy decode replays
    bit-identically).  The serving pool applies this at PAGE granularity:
    its scale planes are per-(token-in-page, head), so ``absmax`` there is
    each cached row's own max — the per-page divergence oracle
    tests/test_serving_paged.py pins against this bound.  Accepts scalars
    or arrays; pure arithmetic, usable host-side."""
    step = absmax / 127.0
    return step if stochastic else step / 2.0


def init_compression_state(params, mesh, axis: str = "data"):
    """Zero error-feedback residual: one residual per shard, stored with an
    explicit leading shard axis (leaf shape ``(W,) + param.shape``) and
    sharded over ``axis`` — each device's slice is ITS residual.  The
    leading axis makes the per-device divergence visible in the type
    instead of hiding divergent buffers behind a fake replicated sharding,
    so the residual survives checkpointing/host round-trips intact."""
    from jax.sharding import NamedSharding

    w = mesh.shape[axis]
    return jax.tree.map(
        lambda p: jax.device_put(
            jnp.zeros((w,) + p.shape, p.dtype),
            NamedSharding(mesh, P(axis)),
        ),
        params,
    )


def make_compressed_dp_train_step(
    loss_fn,
    optimizer,
    mesh,
    axis: str = "data",
    method: str = "topk",
    ratio: float = 0.01,
    donate: bool = False,
):
    """Build ``step(params, opt_state, residual, batch, key) ->
    (params, opt_state, residual, loss)`` — DP gradient aggregation where
    each shard compresses its gradient before the cross-device mean.

    ``method='topk'``: top-``ratio`` sparsification + error-feedback
    residual (init with :func:`init_compression_state`; pass the returned
    residual back in each step).
    ``method='int8'``: stochastic int8 quantization (unbiased, stateless —
    the residual is threaded but unused so both methods share a signature).
    """
    if method not in ("topk", "int8"):
        raise ValueError(f"unknown compression method {method!r}")

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P()),
        out_specs=(P(), P(), P(axis), P()),
        check_vma=False,
    )
    def spmd_step(params, opt_state, residual, batch, key):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # decorrelate shards' stochastic rounding
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        if method == "topk":
            # residual leaves arrive as this shard's (1, ...) slice
            grads = jax.tree.map(
                lambda g, r: g + r[0], grads, residual
            )
            grads, dropped = topk_sparsify(grads, ratio)
            residual = jax.tree.map(lambda d: d[None], dropped)
        else:
            grads = quantize_int8(grads, key)
        grads = jax.lax.pmean(grads, axis)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, residual, jax.lax.pmean(loss, axis)

    step = jax.jit(spmd_step, donate_argnums=(0, 1, 2) if donate else ())

    def _collective_signature(params, opt_state, residual, batch, key):
        # one pmean per (compressed-but-dense) grad leaf + the loss scalar
        # — see the module docstring: the wire payload stays dense
        return [("pmean", tree_nr_leaves(params) + 1,
                 tree_payload_bytes(params) + 4)]

    return instrument_collectives(step, _collective_signature,
                                  op=f"dp_{method}")
