"""Cost-attribution profile plane (obs/profile.py, obs/capacity.py,
tools/calibrate.py):

- the step profiler's capture is a pure function of what was recorded
  (insertion order never leaks), rings and group counts are bounded,
- the deterministic least-squares fit recovers planted linear
  coefficients exactly and degrades to intercept-only on thin or
  singular data; two runs of ``tools/calibrate.py`` over the same
  capture write the byte-identical versioned ``calib_*.json``, and the
  artifact loads & predicts in a process that never imports jax,
- with no profiler installed the instrumented serving and FL paths are
  bit-identical to an uninstrumented build — ServedTokens from the real
  ``ContinuousBatcher`` and FL round outputs from the real engine,
- the capacity scorer is scored, not trusted: sustained drift past the
  threshold fires the ``capacity.recalibrate_hint`` event and counter,
  and the autoscaler / router policy consult the model exactly on cold
  replicas (``_chunk_s == 0``) and nowhere else.
"""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import pytest

from ddl25spring_tpu import obs
from ddl25spring_tpu.obs.capacity import (CALIB_SCHEMA, CapacityModel,
                                          CapacityScorer, CostModel,
                                          fit_cost_model, load_calibration,
                                          roofline_join, save_calibration)
from ddl25spring_tpu.obs.profile import StepProfiler

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def clean_obs():
    yield
    obs.uninstall_profiler()
    obs.uninstall_capacity()
    obs.disable()


def _capture_from(samples, seed=0):
    """Build a capture by recording ``(phase, cov, seconds)`` rows."""
    prof = StepProfiler(seed=seed)
    for phase, cov, s in samples:
        prof.record(phase, seconds=s, **cov)
    return prof.capture()


# -- profiler mechanics ------------------------------------------------------


def test_profiler_capture_canonical_and_seeded():
    rows = [("serving.decode", {"occupancy": o, "chunk": 4}, 0.01 * o)
            for o in (1, 2, 3)]
    a = _capture_from(rows, seed=3)
    b = _capture_from(list(reversed(rows)), seed=3)  # insertion order flipped
    assert a == b
    assert a["schema"] == "ddl25spring.profile.v1"
    # the root is a pure function of the seed, like the req-trace root
    assert a["root"] == StepProfiler(seed=3).root
    assert a["root"] != _capture_from(rows, seed=4)["root"]
    # groups come out in canonical covariate order
    covs = [g["covariates"]["occupancy"]
            for g in a["phases"]["serving.decode"]]
    assert covs == sorted(covs)


def test_profiler_bounds_rings_and_evicts_groups():
    with pytest.raises(ValueError):
        StepProfiler(capacity=0)
    with pytest.raises(ValueError):
        StepProfiler(max_groups=0)
    prof = StepProfiler(capacity=2, max_groups=2)
    for k in range(5):
        prof.record("p", seconds=float(k), occupancy=1)
    # ring keeps only the newest ``capacity`` samples
    (group,) = prof.capture()["phases"]["p"]
    assert group["seconds"] == [3.0, 4.0]
    # a third distinct covariate group evicts the oldest-touched one
    prof.record("p", seconds=1.0, occupancy=2)
    prof.record("p", seconds=1.0, occupancy=1)   # touch group 1 again
    prof.record("p", seconds=1.0, occupancy=3)   # evicts occupancy=2
    assert prof.nr_groups() == 2
    occs = {g["covariates"]["occupancy"]
            for g in prof.capture()["phases"]["p"]}
    assert occs == {1, 3}
    assert prof.phases() == ["p"]
    assert prof.phase_mean_seconds("missing") is None


def test_profiler_counts_samples_through_registry(clean_obs):
    t = obs.enable()
    prof = obs.install_profiler(seed=0)
    assert obs.profiler() is prof
    prof.record("serving.decode", seconds=0.01, occupancy=1)
    prof.record("serving.decode", seconds=0.02, occupancy=2)
    prof.record("fl.round", seconds=0.5, cohort=8)
    assert t.counter("profile_samples_total",
                     phase="serving.decode").value == 2
    assert t.counter("profile_samples_total", phase="fl.round").value == 1
    assert len(prof) == 3
    d = prof.describe()
    assert d["fl.round"]["samples"] == 1
    obs.uninstall_profiler()
    assert obs.profiler() is None


# -- deterministic fit -------------------------------------------------------


def test_fit_recovers_planted_linear_model():
    # seconds = 0.01 + 0.002*occupancy + 0.0005*chunk, exactly; a string
    # covariate and a constant covariate must not perturb the fit
    rows = []
    for occ in (1, 2, 3, 4):
        for chunk in (4, 8):
            rows.append(("serving.decode",
                         {"occupancy": occ, "chunk": chunk,
                          "layout": "paged", "batch": 8},
                         0.01 + 0.002 * occ + 0.0005 * chunk))
    model = fit_cost_model(_capture_from(rows), min_samples=4)
    pm = model.phases["serving.decode"]
    assert pm["features"] == ["chunk", "occupancy"]   # sorted, batch dropped
    assert pm["fit_mean_rel_err"] < 1e-9
    got = model.predict("serving.decode", occupancy=3, chunk=8)
    assert got == pytest.approx(0.01 + 0.006 + 0.004, rel=1e-9)
    # absent covariates fill with capture means — still a finite answer
    filled = model.predict("serving.decode", occupancy=2)
    assert filled == pytest.approx(0.01 + 0.004 + 0.0005 * 6, rel=1e-9)
    assert model.predict("unknown.phase") is None
    assert model.phase_mean("serving.decode") == pytest.approx(
        sum(s for _, _, s in rows) / len(rows), rel=1e-9)


def test_fit_falls_back_to_intercept_only():
    # under min_samples: the phase mean, no features
    thin = _capture_from([("p", {"occupancy": k}, 0.1 * (k + 1))
                          for k in range(3)])
    pm = fit_cost_model(thin, min_samples=8).phases["p"]
    assert pm["features"] == [] and len(pm["coef"]) == 1
    assert pm["coef"][0] == pytest.approx(0.2)
    # singular design (two perfectly collinear covariates) must not
    # crash — Gaussian elimination detects it and degrades the same way
    co = _capture_from([("p", {"a": k, "b": 2 * k}, 0.1) for k in range(6)])
    pm = fit_cost_model(co, min_samples=2).phases["p"]
    assert pm["coef"][0] == pytest.approx(0.1)
    # prediction clamps at the positive floor, never negative
    down = _capture_from([("p", {"x": k}, 0.5 - 0.1 * k) for k in range(5)])
    m = fit_cost_model(down, min_samples=2)
    assert m.predict("p", x=100) > 0


def test_cost_model_version_and_roundtrip(tmp_path):
    rows = [("p", {"x": k}, 0.01 * (k + 1)) for k in range(6)]
    cap = _capture_from(rows)
    m1 = fit_cost_model(cap)
    m2 = fit_cost_model(cap)
    assert m1.version == m2.version
    assert m1.version != fit_cost_model(
        _capture_from(rows[:-1])).version      # different capture, new name
    # save twice -> byte-identical artifact named by the version
    p1 = save_calibration(m1, tmp_path / "a")
    p2 = save_calibration(m2, tmp_path / "b")
    assert p1.name == f"calib_{m1.version[:12]}.json" == p2.name
    assert p1.read_bytes() == p2.read_bytes()
    loaded = load_calibration(p1)
    assert loaded.version == m1.version
    assert loaded.predict("p", x=3) == pytest.approx(
        m1.predict("p", x=3), rel=1e-12)
    with pytest.raises(ValueError):
        CostModel.from_json({"schema": "nope", "version": "v", "phases": {}})


def test_calibrate_cli_byte_identical_and_jax_free(tmp_path):
    cap = _capture_from([("serving.decode", {"occupancy": o, "chunk": 4},
                          0.01 + 0.002 * o)
                         for o in (1, 2, 3, 4, 1, 2, 3, 4)])
    cap_path = tmp_path / "capture.json"
    cap_path.write_text(json.dumps(cap))
    outs = []
    for sub in ("r1", "r2"):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "calibrate.py"),
             str(cap_path), "--out-dir", str(tmp_path / sub),
             "--min-samples", "2", "--no-roofline"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        outs.append(Path(proc.stdout.strip().splitlines()[-1]))
    assert outs[0].name == outs[1].name
    assert outs[0].read_bytes() == outs[1].read_bytes()
    # the artifact loads and predicts without jax ever being imported —
    # the fleet-twin / router consumption contract
    check = (
        "import json, sys\n"
        "from ddl25spring_tpu.obs.capacity import load_calibration\n"
        f"m = load_calibration({str(outs[0])!r})\n"
        "p = m.predict('serving.decode', occupancy=2, chunk=4)\n"
        "assert p is not None and p > 0, p\n"
        "assert 'jax' not in sys.modules\n"
        "print('jaxfree ok', m.version[:12])\n"
    )
    proc = subprocess.run([sys.executable, "-c", check],
                          capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "jaxfree ok" in proc.stdout


# -- roofline join -----------------------------------------------------------


def test_roofline_join_hand_computed():
    peaks = {"flops_per_s": 2.0e12, "hbm_bytes_per_s": 1.0e11}
    rows = roofline_join(
        {"fl.round": 1.0, "serving.decode": 0.0, "orphan": 1.0},
        {"fl.round": {"flops": 1.0e12, "bytes": 2.0e10},
         "serving.decode": {"flops": 1, "bytes": 1},
         "other": {"flops": 1, "bytes": 1}},
        peaks)
    # zero-seconds and unjoined phases drop out
    assert [r["phase"] for r in rows] == ["fl.round"]
    row = rows[0]
    assert row["pct_peak_flops"] == pytest.approx(50.0)
    assert row["pct_peak_hbm"] == pytest.approx(20.0)
    assert row["bound"] == "compute"   # 0.5s ideal flops > 0.2s ideal bytes
    # flip the balance -> memory bound
    (mrow,) = roofline_join({"p": 1.0},
                            {"p": {"flops": 1.0e11, "bytes": 9.0e10}}, peaks)
    assert mrow["bound"] == "memory"
    # missing peaks: join still emits the raw row, no pct/bound fields
    (bare,) = roofline_join({"p": 1.0}, {"p": {"flops": 1, "bytes": 1}}, {})
    assert "pct_peak_flops" not in bare and "bound" not in bare


# -- capacity queries & the drift contract ----------------------------------


def _decode_model(svc=0.01):
    """A cost model whose decode prediction is exactly ``svc``."""
    cap = _capture_from([("serving.decode", {"occupancy": 1}, svc)
                         for _ in range(4)])
    return fit_cost_model(cap, min_samples=2)


def test_capacity_model_wait_math():
    cm = CapacityModel(_decode_model(svc=0.01))
    assert cm.predict_service_s(occupancy=1) == pytest.approx(0.01)
    assert cm.predict_wait_s(6, 2, occupancy=1) == pytest.approx(0.03)
    assert cm.predict_wait_s(0, 2, occupancy=1) == 0.0
    other = CapacityModel(_decode_model(), decode_phase="not.recorded")
    assert other.predict_service_s() is None
    assert other.predict_wait_s(4, 2) is None


def test_scorer_validation_and_install(clean_obs):
    with pytest.raises(ValueError):
        CapacityScorer(_decode_model(), window=0)
    with pytest.raises(ValueError):
        CapacityScorer(_decode_model(), sustain=0)
    with pytest.raises(ValueError):
        obs.install_capacity()
    sc = obs.install_capacity(model=_decode_model())
    assert obs.capacity() is sc
    obs.uninstall_capacity()
    assert obs.capacity() is None


def test_sustained_drift_fires_recalibrate_hint(tmp_path, clean_obs):
    jsonl = tmp_path / "telemetry.jsonl"
    t = obs.enable(str(jsonl))
    model = _decode_model(svc=0.01)
    sc = obs.install_capacity(model=model, threshold=0.2, window=4,
                              sustain=2)
    # accurate observations: gauge publishes per window, no hint
    for _ in range(4):
        assert sc.observe("serving.decode", 0.01, occupancy=1) == \
            pytest.approx(0.0, abs=1e-6)
    assert t.gauge("capacity_model_error",
                   phase="serving.decode").value == pytest.approx(
        0.0, abs=1e-6)
    assert not sc.hints
    # measured 2x the prediction: rel err 0.5 > threshold, but ONE bad
    # window must not hint yet (sustain=2)
    for _ in range(4):
        sc.observe("serving.decode", 0.02, occupancy=1)
    assert not sc.hints
    # the second consecutive bad window fires exactly one hint
    for _ in range(4):
        sc.observe("serving.decode", 0.02, occupancy=1)
    assert len(sc.hints) == 1
    hint = sc.hints[0]
    assert hint["phase"] == "serving.decode"
    assert hint["model_version"] == model.version
    assert hint["mean_rel_err"] == pytest.approx(0.5)
    assert t.counter("capacity_recalibrate_hints_total",
                     phase="serving.decode").value == 1
    assert t.gauge("capacity_model_error",
                   phase="serving.decode").value == pytest.approx(0.5)
    # the event rode the JSONL stream for obs_report
    obs.flush()
    events = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    assert any(e.get("event") == "capacity.recalibrate_hint"
               for e in events)
    # degenerate / unknown observations score nothing
    assert sc.observe("serving.decode", 0.0, occupancy=1) is None
    assert sc.observe("never.seen", 0.01) is None
    d = sc.describe()
    assert d["model_version"] == model.version and len(d["hints"]) == 1


class _ColdReplica:
    """Router-shaped fake: never decoded (``_chunk_s == 0``)."""

    def __init__(self, queue_len):
        self._chunk_s = 0.0
        self._queue = list(range(queue_len))
        self.max_batch = 2
        self.decode_chunk = 0


class _FakeRouter:
    def __init__(self, replicas):
        self.replicas = replicas

    def _eligible(self):
        return range(len(self.replicas))


def test_autoscale_cold_replicas_use_capacity_model(clean_obs):
    from ddl25spring_tpu.serving_fleet import AutoscaleConfig, AutoscalePolicy

    seen = []

    class _Spy(AutoscalePolicy):
        def observe(self, queue_waits, **kw):
            seen.append(list(queue_waits))
            return super().observe(queue_waits, **kw)

    pol = _Spy(AutoscaleConfig(), baseline=2)
    router = _FakeRouter([_ColdReplica(6), _ColdReplica(0)])
    # without a capacity model the cold replicas report an optimistic 0
    pol.observe_fleet(router)
    assert seen[-1] == [0.0, 0.0]
    # with one installed, the queued cold replica contributes its
    # PREDICTED wait: svc * queue_len / max_batch = 0.01 * 6 / 2
    obs.install_capacity(model=_decode_model(svc=0.01))
    pol.observe_fleet(router)
    assert seen[-1] == [pytest.approx(0.03), pytest.approx(0.0)]
    # a warm replica keeps its own measured estimate
    warm = _ColdReplica(4)
    warm._chunk_s = 0.5
    pol.observe_fleet(_FakeRouter([warm]))
    assert seen[-1] == [pytest.approx(0.5 * 4 / 2)]


class _PolicyBatcher:
    """Host-state-only fake batcher for ``snapshot_replica``."""

    def __init__(self, chunk_s):
        self._chunk_s = chunk_s
        self._queue = [1, 2, 3, 4]
        self.slots = []
        self.max_batch = 2
        self.decode_chunk = 0
        self.slo_deadline_s = None

    def _admission_wait_estimate(self, budget):
        return self._chunk_s * 7.0, "lower-bound"


def test_policy_snapshot_cold_replica_uses_capacity_model():
    from ddl25spring_tpu.serving_fleet.policy import snapshot_replica

    cm = CapacityModel(_decode_model(svc=0.01))
    # cold replica: the model's prediction replaces the placeholder 0
    cold = snapshot_replica(0, _PolicyBatcher(0.0), [1, 2], 4,
                            capacity_model=cm)
    assert cold.est_wait_s == pytest.approx(0.01 * 4 / 2)
    # same replica without the model keeps the batcher's own estimate
    bare = snapshot_replica(0, _PolicyBatcher(0.0), [1, 2], 4)
    assert bare.est_wait_s == 0.0
    # a warm replica is never overridden
    warm = snapshot_replica(0, _PolicyBatcher(0.1), [1, 2], 4,
                            capacity_model=cm)
    assert warm.est_wait_s == pytest.approx(0.7)


# -- profiling off must cost nothing (the acceptance criterion) --------------


def test_profiling_off_real_batcher_bit_identical(clean_obs):
    import jax
    import jax.numpy as jnp

    from ddl25spring_tpu.models.llama import Llama, LlamaConfig
    from ddl25spring_tpu.models.serving import ContinuousBatcher

    cfg = LlamaConfig(vocab_size=97, dmodel=48, nr_heads=4, nr_kv_heads=2,
                      nr_layers=2, ctx_size=48)
    prompt = jnp.ones((1, 4), jnp.int32)
    params = Llama(cfg).init(jax.random.PRNGKey(0), prompt,
                             positions=jnp.arange(4))
    prompts = [[3, 5, 7], [11, 13], [17, 19, 23, 29]]
    budgets = [5, 4, 3]

    def run(profiled):
        prof = obs.install_profiler(seed=0) if profiled else None
        try:
            b = ContinuousBatcher(cfg, params, max_batch=2, prefill_width=8,
                                  kv_layout="paged", kv_page=8)
            for rid, (p, bud) in enumerate(zip(prompts, budgets)):
                b.submit(rid, p, bud)
            out = {}
            while b.in_flight:
                out.update(b.step())
            capture = prof.capture() if prof else None
        finally:
            obs.uninstall_profiler()
        return ({rid: ([int(t) for t in toks],
                       getattr(toks, "status", "ok"))
                 for rid, toks in out.items()}, capture)

    off, _ = run(profiled=False)
    on, capture = run(profiled=True)
    assert on == off                       # ServedTokens bit-identical
    # and the profiled run actually measured both serving phases, with
    # the covariates the calibration fit regresses on
    assert {"serving.decode", "serving.prefill"} <= set(capture["phases"])
    dec = capture["phases"]["serving.decode"]
    assert sum(len(g["seconds"]) for g in dec) > 0
    assert all({"occupancy", "batch", "chunk", "pages"} <=
               set(g["covariates"]) for g in dec)
    # a capture this small still round-trips through the fit
    model = fit_cost_model(capture, min_samples=2)
    assert model.predict("serving.decode", occupancy=1) is not None


def test_profiling_off_fl_round_bit_identical(clean_obs):
    import jax

    from ddl25spring_tpu.data import load_mnist, split_dataset
    from ddl25spring_tpu.fl import FedSgdGradientServer, mnist_task

    ds = load_mnist(n_train=256, n_test=64)
    task = mnist_task(ds.test_x, ds.test_y)
    clients = split_dataset(ds.train_x, ds.train_y, nr_clients=4, iid=True,
                            seed=0)

    def one_round(profiled):
        prof = obs.install_profiler(seed=0) if profiled else None
        try:
            server = FedSgdGradientServer(task, lr=0.05, client_data=clients,
                                          client_fraction=0.5, seed=7)
            p1 = server.round_fn(server.params, server.run_key, 0)
            capture = prof.capture() if prof else None
        finally:
            obs.uninstall_profiler()
        return jax.tree.leaves(p1), capture

    base, _ = one_round(profiled=False)
    prof_leaves, capture = one_round(profiled=True)
    import numpy as np
    for a, b in zip(base, prof_leaves):
        assert np.array_equal(np.asarray(a), np.asarray(b))   # bitwise
    (group,) = capture["phases"]["fl.round"]
    assert group["covariates"] == {"cohort": 2, "shards": 1, "chunk": 0}
    assert len(group["seconds"]) == 1


# -- the regression-gate cell ------------------------------------------------


def _load_bench_regression():
    spec = importlib.util.spec_from_file_location(
        "bench_regression", REPO / "tools" / "bench_regression.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_regression_capacity_cell_scaled_threshold():
    br = _load_bench_regression()

    def wrap(err):
        return {"parsed": {"value": 1.0,
                           "cpu_fallback": {
                               "capacity_model": {"mean_rel_err": err}}}}

    # +50% on the error is CPU noise: under the 10x-scaled gate
    rows = br.compare_bench(wrap(0.10), wrap(0.15), threshold=0.10)
    cell = {r["cell"]: r for r in rows}[
        "cpu_fallback.capacity_model.mean_rel_err"]
    assert not cell["regressed"]
    # but a multiple-of-itself jump trips it (>= 10 * 10%)
    rows = br.compare_bench(wrap(0.10), wrap(0.25), threshold=0.10)
    cell = {r["cell"]: r for r in rows}[
        "cpu_fallback.capacity_model.mean_rel_err"]
    assert cell["regressed"]
    # the headline cell still gates at the unscaled threshold
    rows = br.compare_bench(
        {"parsed": {"value": 1.0}}, {"parsed": {"value": 0.8}},
        threshold=0.10)
    assert rows[0]["cell"] == "value" and rows[0]["regressed"]
