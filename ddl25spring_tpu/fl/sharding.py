"""DrJAX-style cohort-sharding primitives for the FL round.

DrJAX (arXiv 2403.07128) expresses a federated round as MapReduce over a
dedicated ``clients`` mesh axis: ``map_clients`` runs the per-client
computation on each shard's slice of the sampled cohort, and the reduce
primitives combine per-shard PARTIAL reductions with one ``psum`` over the
axis — so the update stack, the backward-pass temporaries, and the local
training FLOPs all scale with ``cohort / W`` per replica instead of the
whole cohort.  ``engine.make_fl_round`` / ``fedbuff.make_fedbuff_round``
build their sharded paths from these three primitives plus the shared
chunk-scan discipline (``client_chunk`` streams chunks WITHIN each shard).

Reduction algebra and bit-exactness (the contract tests/test_fl_sharded.py
pins):

- integer reductions (fault stats, secagg's uint32 modular field sums) are
  order-independent, so sharded == local must hold BITWISE at any world
  size — uint32 addition mod 2³² is associative and commutative;
- float reductions change only the summation ORDER (per-shard partials,
  then one psum), the same class of difference as the ``client_chunk``
  streaming accumulator — shard count 1 is bit-identical to the local
  program by construction, larger worlds match within summation-order
  tolerance.

The primitives run INSIDE a ``shard_map`` body (``map_clients`` is the
wrapper that opens one); they lower to a single all-reduce over ICI when
the mesh axis spans devices, and to the identity at world size 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.compat import shard_map
from ..utils.trees import tree_weighted_mean

CLIENTS_AXIS = "clients"


def axis_world(mesh, axis: str = CLIENTS_AXIS) -> int:
    """Extent of the clients axis (the shard-map world size W)."""
    return mesh.shape[axis]


def map_clients(body, mesh, axis: str = CLIENTS_AXIS,
                nr_replicated: int = 1):
    """Wrap ``body`` as a shard_map program over the clients axis.

    ``body(*replicated, *per_client)`` receives the first
    ``nr_replicated`` arguments replicated (``P()`` — params, cohort-global
    id/liveness vectors, scalars) and every remaining argument sharded on
    its LEADING axis (``P(axis)`` — the sampled-cohort slice this shard
    owns).  Outputs must already be replicated when they leave the body:
    reduce them with :func:`reduce_sum` / :func:`reduce_weighted` (which
    end in a ``psum``) before returning.  Axes of ``mesh`` other than
    ``axis`` (e.g. a multihost ``dcn`` axis) stay replicated throughout.
    """

    def run(*args):
        nr_sharded = len(args) - nr_replicated
        in_specs = (P(),) * nr_replicated + (P(axis),) * nr_sharded
        return shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False,
        )(*args)

    return run


def shard_positions(nr_cohort: int, mesh, axis: str = CLIENTS_AXIS):
    """Global cohort positions owned by the calling shard (use inside a
    :func:`map_clients` body): shard ``s`` of ``W`` owns the contiguous
    block ``[s·(nr/W), (s+1)·(nr/W))`` — the same layout ``P(axis)``
    gives the sharded operands."""
    shard = nr_cohort // axis_world(mesh, axis)
    return jax.lax.axis_index(axis) * shard + jnp.arange(shard)


def reduce_sum(tree, axis: str = CLIENTS_AXIS):
    """Cross-shard sum of a pytree of per-shard partial reductions (one
    logical psum per leaf).  Exact for integer/uint32 leaves — modular
    addition commutes — which is what keeps fault stats order-exact and
    secagg field sums bitwise identical to the local path."""
    return jax.tree.map(lambda l: jax.lax.psum(l, axis), tree)


def reduce_weighted(updates, weights, axis: str = CLIENTS_AXIS):
    """Weighted-sum reduction over the cohort: each shard computes its
    partial Σᵢ wᵢ·uᵢ over its LOCAL rows (``tree_weighted_mean`` with
    unnormalized weights IS that partial sum), then one psum combines the
    shards.  Returns ``(sum_tree, weight_sum)`` — the caller performs the
    single normalizing divide, so the float structure matches the
    ``client_chunk`` streaming accumulator."""
    partial = tree_weighted_mean(updates, weights)
    return reduce_sum((partial, jnp.sum(weights)), axis)


def ring_all_reduce(tree, axis: str = CLIENTS_AXIS, world: int = 1):
    """Overlap-friendly all-reduce: ring reduce-scatter + ring all-gather
    built from ``lax.ppermute`` neighbour exchanges instead of one blocking
    ``psum`` per leaf (the arXiv 2004.13336 cross-replica-sharding
    discipline).  Issued per cohort chunk inside the ``client_chunk`` scan,
    the 2·(W-1) pipelined neighbour steps of chunk c overlap chunk c+1's
    client-update map, where the end-of-round ``psum`` serializes.

    Exactness contract (what tests/test_fl_overlap.py pins):

    - ``world == 1`` is the IDENTITY — bit-identical to ``psum`` and to
      the overlap=off program by construction;
    - every shard computes row r of the reduce-scatter as the SAME fixed
      summation order ``Σ_j parts[(r-j) % W]`` and the all-gather copies
      that one value verbatim, so the result is bitwise identical across
      shards (safe under ``out_specs=P()`` with ``check_vma=False``);
    - integer/uint32 leaves (fault stats, secagg field sums) are modular
      and order-independent — bitwise equal to ``psum`` at ANY world;
    - float leaves differ from ``psum`` only in summation order (~1e-7
      per combine, same class as the chunk-streaming accumulator).

    ``world`` must be the static extent of ``axis`` (the shard_map caller
    knows it from the mesh); the ring is unrolled ``2·(world-1)`` steps.
    """
    if world == 1:
        return tree

    fwd = [(s, (s + 1) % world) for s in range(world)]

    def ring_leaf(leaf):
        leaf = jnp.asarray(leaf)
        shape, dtype = leaf.shape, leaf.dtype
        flat = leaf.reshape(-1)
        nr = flat.shape[0]
        row = -(-nr // world)
        flat = jnp.pad(flat, (0, world * row - nr))
        parts = flat.reshape(world, row)
        idx = jax.lax.axis_index(axis)
        # Reduce-scatter: after W-1 steps shard s holds the full sum of
        # row s, accumulated in the shard-independent order Σ_j parts_{s-j}.
        acc = jnp.take(parts, (idx - 1) % world, axis=0)
        for k in range(1, world):
            acc = jax.lax.ppermute(acc, axis, fwd)
            acc = acc + jnp.take(parts, (idx - 1 - k) % world, axis=0)
        # All-gather: circulate each finished row W-1 further steps; the
        # value placed at row (s-k) originated on shard s-k — a verbatim
        # copy, so all shards assemble the same bits.
        out = jnp.zeros((world, row), dtype)
        cur = acc
        out = jax.lax.dynamic_update_index_in_dim(out, cur, idx, 0)
        for k in range(1, world):
            cur = jax.lax.ppermute(cur, axis, fwd)
            out = jax.lax.dynamic_update_index_in_dim(
                out, cur, (idx - k) % world, 0)
        return out.reshape(-1)[:nr].reshape(shape)

    return jax.tree.map(ring_leaf, tree)


def ring_broadcast(tree, axis: str = CLIENTS_AXIS, world: int = 1,
                   source: int = 0):
    """Broadcast ``source``'s pytree to every shard over the SAME ring
    schedule as :func:`ring_all_reduce` — the rollout plane's cross-replica
    weight-delta distribution (arXiv 2004.13336) reuses the reduce path
    instead of growing a second collective: every shard other than
    ``source`` contributes zeros, so the ring sum IS the broadcast.

    Exactness: ``world == 1`` is the identity.  Larger worlds are bitwise
    equal to the source's leaves for every value except IEEE ``-0.0``
    (``-0.0 + 0.0 == +0.0``, so negative zeros arrive as positive zeros —
    numerically equal, one sign bit off).  Weight deltas hitting an exact
    ``-0.0`` are vanishingly rare and the rollout plane's bit-exactness
    oracle checks the RECONSTRUCTED params, which go through the same
    addition, so the contract holds where it matters.
    """
    if world == 1:
        return tree
    masked = jax.tree.map(
        lambda l: jnp.where(jax.lax.axis_index(axis) == source,
                            jnp.asarray(l), jnp.zeros_like(l)),
        tree)
    return ring_all_reduce(masked, axis, world)


def ppermute_signature(tree, extra_scalar_leaves: int = 0, world: int = 1,
                       nr_combines: int = 1):
    """Host-side collective signature of the overlapped (ring) combine for
    ``instrument_collectives``: each of the ``nr_combines`` per-chunk
    combines moves every leaf (plus scalars) through ``2·(W-1)`` ppermute
    steps, each step carrying ``payload / W`` bytes — the classic ring
    all-reduce wire volume of ``2·(W-1)/W`` times the payload."""
    from ..parallel.collectives import tree_nr_leaves, tree_payload_bytes

    if world <= 1:
        return [("ppermute", 0, 0)]
    leaves = tree_nr_leaves(tree) + extra_scalar_leaves
    nbytes = tree_payload_bytes(tree) + 4 * extra_scalar_leaves
    steps = 2 * (world - 1)
    return [("ppermute", nr_combines * leaves * steps,
             nr_combines * (nbytes * steps) // world)]


def psum_signature(tree, extra_scalar_leaves: int = 0):
    """Host-side collective signature of one sharded-round dispatch for
    ``parallel.collectives.instrument_collectives``: one logical psum per
    array leaf of ``tree`` (the partial-reduction payload) plus
    ``extra_scalar_leaves`` scalar psums (weight sum, contributor count,
    stats vector...).  Pure shape math — safe to call with ShapeDtypeStruct
    trees."""
    from ..parallel.collectives import tree_nr_leaves, tree_payload_bytes

    calls = tree_nr_leaves(tree) + extra_scalar_leaves
    nbytes = tree_payload_bytes(tree) + 4 * extra_scalar_leaves
    return [("psum", calls, nbytes)]
