"""Pallas flash-attention vs the dense XLA reference (interpret mode on CPU;
the same kernels compile on TPU)."""

import jax
import jax.numpy as jnp
import pytest

from ddl25spring_tpu.ops.attention import causal_attention
from ddl25spring_tpu.ops.flash_attention import flash_causal_attention


@pytest.fixture(scope="module")
def qkv():
    B, T, H, d = 2, 64, 2, 16
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (B, T, H, d)) for k in ks)


def test_flash_forward_matches_dense(qkv):
    q, k, v = qkv
    out = flash_causal_attention(q, k, v, interpret=True)
    ref = causal_attention(q, k, v)
    assert jnp.allclose(out, ref, atol=1e-4)


def test_flash_grads_match_dense(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        assert jnp.allclose(a, b, atol=1e-3), jnp.abs(a - b).max()


def test_flash_in_llama_forward():
    import dataclasses

    from ddl25spring_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig(vocab_size=64, dmodel=32, nr_heads=2, nr_layers=2,
                      ctx_size=32)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 64)
    model = Llama(cfg)
    params = model.init(jax.random.key(2), tokens)
    ref = model.apply(params, tokens)
    flash_model = Llama(dataclasses.replace(cfg, attn_impl="flash"))
    out = flash_model.apply(params, tokens)
    assert jnp.allclose(out, ref, atol=2e-4), jnp.abs(out - ref).max()


def test_flash_bf16_matches_f32_reference(qkv):
    """The kernels keep matmul operands in their storage dtype (bf16 on the
    LM path) with f32 accumulators — the only behavior the storage-dtype
    path changes vs the f32 tests above, so it needs its own oracle: bf16
    flash output and grads must track the float32 dense reference to bf16
    tolerance."""
    q32, k32, v32 = qkv
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)

    out = flash_causal_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = causal_attention(q32, k32, v32)
    # bf16 has ~3 decimal digits; the online softmax + f32 accumulation must
    # not add error beyond input-rounding scale
    assert jnp.max(jnp.abs(out.astype(jnp.float32) - ref)) < 0.03

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, interpret=True)
                       .astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, (0, 1, 2))(q32, k32, v32)
    for a, b in zip(g_flash, g_dense):
        assert a.dtype == jnp.bfloat16
        err = jnp.max(jnp.abs(a.astype(jnp.float32) - b))
        scale = jnp.max(jnp.abs(b)) + 1e-6
        assert err / scale < 0.05, (err, scale)
