"""Pallas TPU flash-attention (causal) — forward and backward kernels.

The hot op of the LLM path (SURVEY.md §2.3: attention lives inside the
reference's ``simplellm`` dependency, running whatever torch does; here it is
a hand-tiled TPU kernel).  Standard flash-attention construction (Dao et al.,
public): the (T, T) score matrix is never materialised — each q-block streams
over its causal k/v-blocks, maintaining the online-softmax running max/sum,
and the backward recomputes block scores from the saved per-row logsumexp
instead of storing probabilities.

Every kernel tiles K/V (and in the dk/dv pass, Q) over the innermost GRID
axis with float32 accumulators in VMEM scratch, so VMEM use is bounded by
the block sizes alone — sequence length only grows the grid.  (An earlier
revision kept the whole K/V window resident in VMEM, which capped T at ~8k
on v5e; this construction has no such cap.)  Causality is exploited by
masking the diagonal block and skipping fully-masked blocks via ``pl.when``.

Complexities: O(T²) compute (halved by causal skipping), O(T) memory.  The
XLA fallback (ops.attention.causal_attention) materialises the full
(B, H, T, T) score tensor.

Layout notes: kernels fuse (B*H) into the leading grid axis; the per-row
logsumexp rides as (BH, 1, T) so its (1, 1, block) tiles keep the trailing
(sublane, lane) shape Mosaic-legal — a 2-D (1, block) tile of a (BH, T)
array is rejected on real TPUs (interpret mode never checks this).

Throughput notes: per-step pipeline overhead dominates at small blocks (the
128-block revision spent ~500 ms at T=32k on ~400k grid steps of ~3 MFLOP
each), so blocks default to 512 (``BLOCK_TARGET``); matmul operands stay in
their storage dtype (bf16 in the LM path) with f32 ``preferred_element_type``
accumulation — the MXU's native mode — instead of upcasting to f32 first.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Default q/k block edge.  Bigger blocks are the single largest throughput
# lever on TPU: total grid steps = BH * (T/bq) * (T/bk) and each step has a
# fixed pipeline cost, so 128->512 cuts step count 16x while each step's
# matmuls grow into solidly MXU-shaped (512, d)x(d, 512) tiles.  512 keeps
# the worst-case VMEM residency (bwd dkv: four operand blocks + two f32
# accumulators + the (bq, bk) f32 score/prob intermediates) around 4 MB at
# head_dim 128 — comfortably inside a v5e core's ~16 MB shared VMEM with
# double buffering.
BLOCK_TARGET = 512


def _pick_block(t: int, target: int = BLOCK_TARGET) -> int:
    b = min(t, target)
    while t % b:
        b -= 1
    return b


def _kv_clamp(i, j, block_q, block_k):
    """KV block index for (q block i, step j): masked upper-triangle steps
    clamp to the diagonal block, so the pipeline sees a repeated index and
    skips the DMA (the ``pl.when`` guard already skips the compute)."""
    return jnp.minimum(j, ((i + 1) * block_q - 1) // block_k)


def _q_clamp(i, j, block_q, block_k):
    """Q block index for (k block j, step i): steps before the first
    contributing q block clamp to it, skipping their DMA."""
    return jnp.maximum(i, (j * block_k) // block_q)


# Every kernel takes a static ``causal`` flag.  causal=True is the standard
# single-device op (diagonal masking, upper-triangle compute+DMA skipping);
# causal=False computes FULL attention of q against this k/v — the building
# block of the sequence-parallel ring (ops/ring_flash.py), where a device's
# queries attend to an earlier device's keys with no masking at all.  The
# flag is resolved at trace time, so the False path carries no mask code.


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
                *, block_q, block_k, scale, nr_kv, causal):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    # causal: block j contributes iff its first key position is visible to
    # the q block's last query position (non-causal: every block contributes,
    # so the guard disappears at trace time)
    def _compute():
        # matmul operands stay in their storage dtype (bf16 from the model):
        # the MXU natively accumulates bf16 x bf16 into f32
        # (preferred_element_type), which is both faster than upcast-then-f32
        # matmul and just as accurate where it matters (the accumulator)
        q = q_ref[0]                                  # (block_q, d)
        k = k_ref[0]                                  # (block_k, d)
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_old = m_scr[...]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_old - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=-1)
        acc[...] = acc[...] * corr[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(j * block_k < (qi + 1) * block_q)(_compute)
    else:
        _compute()

    @pl.when(j == nr_kv - 1)
    def _finalize():
        l = l_scr[...]
        o_ref[0] = (acc[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log(l)


def _flash_fwd(q, k, v, *, block_q, block_k, interpret, causal):
    BH, T, d = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    nr_kv = Tk // block_k
    grid = (BH, T // block_q, nr_kv)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        nr_kv=nr_kv, causal=causal,
    )
    if causal:
        # clamp masked upper-triangle steps to the diagonal block: the
        # pipeline skips the DMA when the block index repeats, so causal
        # skipping saves K/V bandwidth, not just compute
        kv_map = lambda b, i, j: (b, _kv_clamp(i, j, block_q, block_k), 0)
    else:
        kv_map = lambda b, i, j: (b, j, 0)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse rides as (BH, 1, T): a (1, 1, block_q) block keeps the
            # trailing (sublane, lane) = (1, block_q) legal for Mosaic
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, block_q, block_k, scale, nr_kv, causal):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[:, None])
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] = dq_scr[...] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(j * block_k < (qi + 1) * block_q)(_compute)
    else:
        _compute()

    @pl.when(j == nr_kv - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, block_q, block_k, scale, nr_q, causal):
    ki = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        k = k_ref[0]                                  # (block_k, d)
        v = v_ref[0]
        q = q_ref[0]                                  # (block_q, d)
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[:, None])
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv_scr[...] = dv_scr[...] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[...] = dk_scr[...] + jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )

    if causal:
        # q block i sees k block ki iff its last query >= the block's first key
        pl.when((i + 1) * block_q > ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(i == nr_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, dlse, *, block_q, block_k, interpret,
               causal):
    BH, T, d = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    # delta_i = do_i . o_i - dlse_i: the softmax-backward row correction.
    # With lse exposed as a real output (the ring merge consumes it), its
    # cotangent enters ds_ij = p_ij (do_i . v_j - delta_i) through the same
    # rowwise term — dlse of zeros recovers the classic flash backward.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )[:, None, :] - dlse  # (BH, 1, T), matching lse's Mosaic-legal layout
    nr_q = T // block_q
    nr_kv = Tk // block_k

    if causal:
        kv_map = lambda b, i, j: (b, _kv_clamp(i, j, block_q, block_k), 0)
        q_map = lambda b, j, i: (b, _q_clamp(i, j, block_q, block_k), 0)
        q_row_map = lambda b, j, i: (b, 0, _q_clamp(i, j, block_q, block_k))
    else:
        kv_map = lambda b, i, j: (b, j, 0)
        q_map = lambda b, j, i: (b, i, 0)
        q_row_map = lambda b, j, i: (b, 0, i)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, nr_kv=nr_kv, causal=causal),
        grid=(BH, nr_q, nr_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, nr_q=nr_q, causal=causal),
        grid=(BH, nr_kv, nr_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_q), q_row_map),
            pl.BlockSpec((1, 1, block_q), q_row_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, d), q.dtype),
            jax.ShapeDtypeStruct((BH, Tk, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public ops (custom VJP over (B, T, H, d) layout)
# --------------------------------------------------------------------------

def _to_bh(x):
    B, T, H, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, d)


def _from_bh(x, B, H):
    BH, T, d = x.shape
    return x.reshape(B, H, T, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_block(q, k, v, causal, interpret):
    """(o, lse) of q attending to k/v — causal (Tq == Tk) or full.

    ``lse`` (B, H, Tq) is a REAL output with a real gradient path (the ring
    merge differentiates through it), not just a backward residual."""
    out, lse, _ = _block_core(q, k, v, causal, interpret)
    return out, lse


def _block_core(q, k, v, causal, interpret):
    B, T, H, d = q.shape
    block_q = _pick_block(T)
    block_k = _pick_block(k.shape[1])
    if causal:
        block_q = block_k = min(block_q, block_k)
    o, lse = _flash_fwd(_to_bh(q), _to_bh(k), _to_bh(v),
                        block_q=block_q, block_k=block_k,
                        interpret=interpret, causal=causal)
    return _from_bh(o, B, H), lse.reshape(B, H, T), (o, lse)


def _flash_block_fwd_rule(q, k, v, causal, interpret):
    out, lse_bht, (o_bh, lse) = _block_core(q, k, v, causal, interpret)
    return (out, lse_bht), (q, k, v, o_bh, lse)


def _flash_block_bwd_rule(causal, interpret, res, g):
    do, dlse = g
    q, k, v, o_bh, lse = res
    B, T, H, d = q.shape
    block_q = _pick_block(T)
    block_k = _pick_block(k.shape[1])
    if causal:
        block_q = block_k = min(block_q, block_k)
    dq, dk, dv = _flash_bwd(
        _to_bh(q), _to_bh(k), _to_bh(v), o_bh, lse, _to_bh(do),
        dlse.reshape(B * H, 1, T).astype(jnp.float32),
        block_q=block_q, block_k=block_k, interpret=interpret, causal=causal,
    )
    return _from_bh(dq, B, H), _from_bh(dk, B, H), _from_bh(dv, B, H)


_flash_block.defvjp(_flash_block_fwd_rule, _flash_block_bwd_rule)


#: ``interpret=None`` auto-select override.  AOT TPU-topology compiles
#: (tools/aot_validate.py) trace under a CPU *default* backend while
#: compiling for a TPU *target*, so the backend sniff below would wrongly
#: pick the interpreter; they set this to False for the trace.
INTERPRET_OVERRIDE: bool | None = None


def _resolve_interpret(interpret):
    if interpret is None:
        if INTERPRET_OVERRIDE is not None:
            return INTERPRET_OVERRIDE
        return jax.default_backend() != "tpu"
    return interpret


def flash_causal_attention(q, k, v, *, interpret: bool | None = None):
    """Causal MHA via the Pallas flash kernels.

    Same signature/semantics as ``causal_attention`` — q, k, v are
    (B, T, H, head_dim).  ``interpret=None`` auto-selects: compiled on TPU,
    interpreter elsewhere (so the op works — slowly — in CPU tests).
    """
    o, _ = _flash_block(q, k, v, True, _resolve_interpret(interpret))
    return o


def flash_block_attention(q, k, v, *, causal: bool,
                          interpret: bool | None = None):
    """Blockwise attention returning ``(o, lse)`` — the ring building block.

    ``causal=False`` computes FULL (unmasked) attention of the local queries
    against a remote KV block (Tq and Tk may differ); ``lse`` (B, H, Tq)
    feeds the online log-sum-exp merge that stitches per-block partial
    results into exact global attention (ops.ring_flash).  Gradients flow
    through BOTH outputs.
    """
    if causal and q.shape[1] != k.shape[1]:
        # local 0-based q_pos >= k_pos masking is meaningless when the q
        # block sits elsewhere in the key sequence — fail loudly instead of
        # returning plausible-looking garbage
        raise ValueError(
            f"causal=True needs Tq == Tk (got {q.shape[1]} vs {k.shape[1]})"
        )
    return _flash_block(q, k, v, causal, _resolve_interpret(interpret))
