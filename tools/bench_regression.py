#!/usr/bin/env python3
"""Automatic regression gate over the bench capture protocol.

Compares the newest ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` capture
pair against the previous one (the r06+ measurement protocol of ROADMAP
item 5) and exits non-zero when any *comparable* cell regresses by more
than the threshold (10% by default).

Comparable means both captures carry the cell with a finite, non-zero
previous value.  Device-unreachable captures (``value: 0.0`` with an
``error`` field) contribute nothing except their ``cpu_fallback`` trend
cells, so a dead tunnel is never reported as a code regression — that is
the whole point of the CPU-trend cells riding along in BENCH files.

Cells and their direction:

- ``value`` (rounds/sec) and ``final_test_accuracy_pct`` — higher better;
- ``kernels.*.achieved_gbps`` higher / ``kernels.*.ms`` lower better;
- ``krum_agg.ms`` — lower better;
- ``cohort_scaling.rounds_per_sec.*`` — higher better;
- ``overlap_combine.rounds_per_sec`` / ``fused_decode_step.steps_per_sec``
  — higher better (the overlapped ring combine and the one-Pallas-program
  serving inner step);
- ``serving_saturation`` / ``fleet_routing`` ``probe_goodput_rps`` and
  ``knee_qps`` — higher better;
- ``fleet_chaos.goodput_retention`` — higher better;
- ``fleet_rollout.goodput_retention`` — higher better — and
  ``fleet_rollout.rollback_latency_s`` — lower better (the weight-push
  plane's overhead under live load and its auto-revert cost);
- ``multi_tenant_serving.goodput_tps`` and
  ``multi_tenant_serving.goodput_ratio_vs_single_tenant`` — higher
  better — and ``multi_tenant_serving.adapter_miss_rate`` — lower
  better (the batched multi-LoRA decode path's goodput vs the null-
  adapter baseline and the adapter pool's residency pressure);
- ``capacity_model.mean_rel_err`` — lower better (predicted-vs-measured
  error of the calibrated step-cost model on the serving trend cell;
  gated at 10x the base threshold because the healthy value is a small
  ratio measured from CPU timing jitter);
- ``kv_quant_tiered.*.tokens_per_sec``,
  ``kv_quant_tiered.resident_drop_f32_vs_int8_spill`` and
  ``kv_quant_tiered.goodput_ratio_int8_spill_vs_f32`` — higher better
  (the quantized/tiered KV pool cell: per-layout goodput, the
  device-resident KV-per-stream drop int8+spill buys, and how much
  goodput the spill tier costs);
- MULTICHIP ``ok`` flipping true→false, or ``n_devices`` shrinking.

Zero deps beyond the stdlib (the tier-1 suite runs ``--dry-run`` as a
gate-of-the-gate).  Exit codes: 0 clean / nothing to compare, 1 at least
one regression (suppressed by ``--dry-run``), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from pathlib import Path

_NUM = re.compile(r"_r?(\d+)\.json$")

# (dotted path into the parsed dict, higher_is_better[, threshold_scale]);
# kernels and cohort_scaling fan out over their dynamic keys below.  The
# optional third element scales the gate threshold for cells whose
# healthy run-to-run noise exceeds the default band (the capacity-model
# error is a small ratio measured from CPU timing jitter: only a
# multiple-of-itself jump means the calibration fit regressed).
_SCALAR_CELLS = (
    ("value", True),
    ("final_test_accuracy_pct", True),
    ("krum_agg.ms", False),
    ("overlap_combine.rounds_per_sec", True),
    ("fused_decode_step.steps_per_sec", True),
    ("serving_saturation.probe_goodput_rps", True),
    ("serving_saturation.knee_qps", True),
    ("fleet_routing.probe_goodput_rps", True),
    ("fleet_routing.knee_qps", True),
    ("fleet_chaos.goodput_retention", True),
    ("fleet_rollout.goodput_retention", True),
    ("fleet_rollout.rollback_latency_s", False),
    ("multi_tenant_serving.goodput_tps", True),
    ("multi_tenant_serving.goodput_ratio_vs_single_tenant", True),
    ("multi_tenant_serving.adapter_miss_rate", False),
    ("capacity_model.mean_rel_err", False, 10.0),
    ("kv_quant_tiered.f32.tokens_per_sec", True),
    ("kv_quant_tiered.int8.tokens_per_sec", True),
    ("kv_quant_tiered.int8_spill.tokens_per_sec", True),
    ("kv_quant_tiered.resident_drop_f32_vs_int8_spill", True),
    ("kv_quant_tiered.goodput_ratio_int8_spill_vs_f32", True),
)


def _capture_index(path: Path) -> int:
    m = _NUM.search(path.name)
    return int(m.group(1)) if m else -1


def find_captures(root: Path, prefix: str) -> list[Path]:
    return sorted(root.glob(f"{prefix}_*.json"), key=_capture_index)


def _dig(d: dict, dotted: str):
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _cells_from(parsed: dict, prefix: str = "") -> dict:
    """``name -> (value, higher_better, threshold_scale)`` for every
    comparable cell in one parsed bench dict (recursing once into
    ``cpu_fallback``)."""
    out: dict = {}
    if not isinstance(parsed, dict):
        return out
    dead = "error" in parsed and not parsed.get("value")
    for spec in _SCALAR_CELLS:
        dotted, higher = spec[0], spec[1]
        scale = spec[2] if len(spec) > 2 else 1.0
        if dead and dotted in ("value", "final_test_accuracy_pct"):
            continue  # device unreachable: the headline never ran
        v = _dig(parsed, dotted)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[prefix + dotted] = (float(v), higher, scale)
    kernels = parsed.get("kernels")
    if isinstance(kernels, dict):
        for kname, cell in sorted(kernels.items()):
            if not isinstance(cell, dict):
                continue
            for field, higher in (("achieved_gbps", True), ("ms", False)):
                v = cell.get(field)
                if isinstance(v, (int, float)) and math.isfinite(v):
                    out[f"{prefix}kernels.{kname}.{field}"] = (
                        float(v), higher, 1.0)
    cohort = _dig(parsed, "cohort_scaling.rounds_per_sec")
    if isinstance(cohort, dict):
        for size, v in sorted(cohort.items()):
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{prefix}cohort_scaling.rounds_per_sec.{size}"] = (
                    float(v), True, 1.0)
    fb = parsed.get("cpu_fallback")
    if isinstance(fb, dict) and not prefix:
        out.update(_cells_from(fb, prefix="cpu_fallback."))
    return out


def compare_bench(prev: dict, new: dict, threshold: float) -> list[dict]:
    """Per-cell comparison rows; a row regresses when the change in the
    *bad* direction exceeds ``threshold`` (relative to previous, scaled
    by the cell's own threshold multiplier)."""
    pcells = _cells_from(prev.get("parsed") or {})
    ncells = _cells_from(new.get("parsed") or {})
    rows = []
    for name in sorted(pcells):
        if name not in ncells:
            continue
        pv, higher, scale = pcells[name]
        nv, _, _ = ncells[name]
        if pv == 0:
            continue  # no meaningful relative change
        change = (nv - pv) / abs(pv)
        bad = -change if higher else change
        rows.append({"cell": name, "prev": pv, "new": nv,
                     "change_pct": round(change * 100, 2),
                     "regressed": bad > threshold * scale})
    return rows


def compare_multichip(prev: dict, new: dict) -> list[dict]:
    rows = []
    if prev.get("skipped") or new.get("skipped"):
        return rows
    if prev.get("ok") and not new.get("ok"):
        rows.append({"cell": "multichip.ok", "prev": True, "new": False,
                     "regressed": True})
    pd, nd = prev.get("n_devices"), new.get("n_devices")
    if isinstance(pd, int) and isinstance(nd, int) and nd < pd:
        rows.append({"cell": "multichip.n_devices", "prev": pd, "new": nd,
                     "regressed": True})
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Gate the newest bench capture against the previous "
                    "one (>threshold regression in a comparable cell "
                    "fails)")
    ap.add_argument("--root", type=Path,
                    default=Path(__file__).resolve().parent.parent,
                    help="directory holding BENCH_*.json / "
                         "MULTICHIP_*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression that fails the gate "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--dry-run", action="store_true",
                    help="report, but always exit 0 (the tier-1 smoke "
                         "mode)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as one JSON object")
    args = ap.parse_args()
    if args.threshold <= 0:
        print("--threshold must be > 0", file=sys.stderr)
        return 2
    if not args.root.is_dir():
        print(f"no such directory: {args.root}", file=sys.stderr)
        return 2

    rows: list[dict] = []
    compared: list[str] = []
    for prefix, cmp_fn in (("BENCH", compare_bench),
                           ("MULTICHIP", compare_multichip)):
        caps = find_captures(args.root, prefix)
        if len(caps) < 2:
            continue
        prev_p, new_p = caps[-2], caps[-1]
        try:
            prev = json.loads(prev_p.read_text())
            new = json.loads(new_p.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"unreadable capture under {prefix}: {e}",
                  file=sys.stderr)
            return 2
        compared.append(f"{prev_p.name} -> {new_p.name}")
        if cmp_fn is compare_bench:
            rows.extend(cmp_fn(prev, new, args.threshold))
        else:
            rows.extend(cmp_fn(prev, new))

    regressions = [r for r in rows if r["regressed"]]
    if args.json:
        print(json.dumps({"compared": compared, "threshold": args.threshold,
                          "cells": rows,
                          "regressions": len(regressions)}, indent=2))
    else:
        if not compared:
            print("bench_regression: fewer than two captures — nothing "
                  "to compare")
        for line in compared:
            print(f"comparing {line}")
        if compared and not rows:
            print("no comparable cells (device-unreachable captures "
                  "carry no trend cells)")
        for r in rows:
            flag = "REGRESSED" if r["regressed"] else "ok"
            if "change_pct" in r:
                print(f"  {r['cell']:<48} {r['prev']:>10g} -> "
                      f"{r['new']:>10g}  {r['change_pct']:>+7.2f}%  {flag}")
            else:
                print(f"  {r['cell']:<48} {r['prev']} -> {r['new']}  "
                      f"{flag}")
        if regressions:
            print(f"{len(regressions)} cell(s) regressed beyond "
                  f"{args.threshold * 100:.0f}%")
    if args.dry_run:
        return 0
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
