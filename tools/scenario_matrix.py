"""Attack x defense scenario matrix over the FL engine.

Sweeps Byzantine update attacks against aggregation defenses on a tiny
synthetic softmax-classification task, one jitted ``make_fl_round`` per
cell, and writes one results JSON per cell plus a summary:

- **attack**: sign-flip (scaled negation), gaussian (pure-noise updates),
  alie (collusive mu + z*sigma) — all injected IN-ROUND via
  ``attack_fraction`` (robust.byzantine_round_mask), so the coalition is
  redrawn every round;
- **aggregator**: mean | median | trimmed-mean | krum
  (robust/aggregators.py);
- **mode**: plain | secagg (group-wise masked sessions, the aggregator
  reduces over decoded GROUP sums — ddl25spring_tpu.secagg with
  ``nr_groups > 1``) | dp (DP-FedAvg clip+noise; mean only) | compress
  (top-k sparsified uplinks);
- **cohort**: sampled clients per round (population is 2x the cohort).

The task is deliberately tiny — a linear softmax probe whose accuracy
collapses under a successful attack and saturates without one — so every
cell is a seconds-scale CPU program and a 1k-client cohort is still only
a [1000, P] stack.  ``--smoke`` runs the 2x2x2 tier-1 matrix
(sign-flip x {mean, median} x {plain, secagg}) the test suite pins: the
robust aggregator must recover final accuracy under a 30% sign-flip
coalition that degrades the weighted mean, in BOTH modes.

Usage:
    python tools/scenario_matrix.py --smoke --out results/scenario_smoke
    python tools/scenario_matrix.py --cohorts 8,32,1024 \
        --out results/scenario_matrix --telemetry results/scenario.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

ATTACKS = ("sign-flip", "gaussian", "alie")
AGGREGATORS = ("mean", "median", "trimmed-mean", "krum")
MODES = ("plain", "secagg", "dp", "compress")


def make_synthetic(nr_clients: int, n_per_client: int, d: int, k: int,
                   seed: int):
    """Linearly separable k-class blobs, IID across clients, plus a
    held-out test split — small enough that the fault-free FedAvg probe
    reaches ~100% in a handful of rounds (headroom for attacks to
    destroy)."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d, k)).astype(np.float32)

    def draw(n):
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = np.argmax(x @ w_true, axis=1).astype(np.int32)
        return x, y

    xs, ys = [], []
    for _ in range(nr_clients):
        x, y = draw(n_per_client)
        xs.append(x)
        ys.append(y)
    test_x, test_y = draw(512)
    return (np.stack(xs), np.stack(ys),
            np.full((nr_clients,), n_per_client, np.int64),
            test_x, test_y)


def build_round(cell: dict, data, seed: int):
    """One jitted engine round for this cell; returns (round_fn, secagg,
    skip_reason).  Infeasible combinations return a reason instead of a
    round (e.g. DP's uniform clip excludes custom aggregators, Krum needs
    rows - f - 2 >= 1 over whatever the rule actually sees)."""
    import jax

    from ddl25spring_tpu.fl.engine import make_fl_round
    from ddl25spring_tpu.robust import (coordinate_median, make_alie_attack,
                                        make_gaussian_attack, make_krum,
                                        make_sign_flip_attack,
                                        make_trimmed_mean)

    x, y, counts, _, _ = data
    cohort = cell["cohort"]
    fraction = cell["attack_fraction"]

    # sign-flip scale > cohort so ONE attacker already flips the round
    # mean (m-1 honest u's vs one -s*u: sum < 0 when s > m-1) — the
    # robust rules are magnitude-insensitive so only the mean cells care
    attack = {
        "sign-flip": lambda: make_sign_flip_attack(cohort + 2.0),
        "gaussian": lambda: make_gaussian_attack(5.0),
        "alie": lambda: make_alie_attack(1.5),
    }[cell["attack"]]()

    mode = cell["mode"]
    secagg = None
    kw = {}
    # the robust rule reduces over per-client updates in plain mode but
    # over decoded GROUP aggregates under grouped secagg
    rows = cohort
    if mode == "secagg":
        from ddl25spring_tpu.secagg import SecAgg

        nr_groups = max(2, cohort // 2)
        secagg = SecAgg(x.shape[0], cohort, counts=counts, clip=8.0,
                        threshold_frac=0.5, seed=seed,
                        nr_groups=nr_groups)
        rows = nr_groups
        kw["secagg"] = secagg
    elif mode == "dp":
        if cell["aggregator"] != "mean":
            return None, None, "dp clips to a UNIFORM-weight mean; custom " \
                               "aggregators are rejected at build time"
        kw.update(dp_clip=2.0, dp_noise_mult=0.1)
    elif mode == "compress":
        kw.update(compress="topk", compress_ratio=0.5)

    f = max(1, round(fraction * rows))
    if cell["aggregator"] == "mean":
        aggregator = None
    elif cell["aggregator"] == "median":
        aggregator = coordinate_median
    elif cell["aggregator"] == "trimmed-mean":
        ratio = min(0.45, f / rows)
        if 2 * int(ratio * rows) >= rows:
            return None, None, f"trimmed-mean needs 2k < m over {rows} rows"
        aggregator = make_trimmed_mean(ratio)
    else:  # krum
        if rows - f - 2 < 1:
            return None, None, f"krum needs rows - f - 2 >= 1 over {rows} " \
                               f"rows (f={f})"
        aggregator = make_krum(f, 1)
    if mode == "secagg" and cell["aggregator"] == "mean":
        # still exercised: grouped masked sums recombined by group weight
        aggregator = None

    import jax.numpy as jnp

    def client_update(params, x_i, y_i, c_i, k_i):
        def loss(p):
            logits = x_i @ p["w"] + p["b"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(
                logp, y_i[:, None].astype(jnp.int32), axis=1))

        p = params
        for _ in range(2):
            g = jax.grad(loss)(p)
            p = jax.tree.map(lambda w, gg: w - 0.5 * gg, p, g)
        return p

    round_fn = make_fl_round(
        client_update, x, y, counts, cohort,
        aggregator=aggregator, attack=attack,
        attack_fraction=fraction, attack_seed=seed + 17,
        **kw,
    )
    return round_fn, secagg, None


def run_cell(cell: dict, nr_rounds: int, seed: int,
             val_gate: str = "restore") -> dict:
    """Execute one cell end-to-end; returns the result row (or the skip
    reason for infeasible combinations).

    Every cell runs behind the same :class:`resilience.ValidationGate`
    (``val_gate`` policy, "" disables): the gate re-scores each round's
    aggregate on the held-out split and refuses rounds that drop below
    best-so-far.  It is applied UNIFORMLY — to mean and robust cells
    alike — so the matrix compares full defense stacks, not aggregators
    in isolation.  The gate matters most for grouped secagg: a group of
    size s is poisoned with probability 1 - (1-p)^s, which at p = 0.3 and
    s = 2 sits right at the coordinate-median breakdown point — the gate
    rejects the majority-poisoned rounds the group-level rule loses
    (docs/SECURITY.md's granularity-vs-robustness tradeoff)."""
    import jax
    import jax.numpy as jnp

    from ddl25spring_tpu import obs
    from ddl25spring_tpu.resilience import ValidationGate

    d, k = 16, 4
    nr_clients = 2 * cell["cohort"]
    data = make_synthetic(nr_clients, 32, d, k, seed)
    _, _, _, test_x, test_y = data
    t0 = time.perf_counter()
    round_fn, secagg, skip = build_round(cell, data, seed)
    if skip is not None:
        return {"cell": cell, "skipped": skip}

    @jax.jit
    def accuracy(params):
        pred = jnp.argmax(test_x @ params["w"] + params["b"], axis=1)
        return 100.0 * jnp.mean((pred == test_y).astype(jnp.float32))

    gate = (ValidationGate(accuracy, policy=val_gate, tolerance=1.0)
            if val_gate else None)
    init = jax.random.normal(jax.random.PRNGKey(seed), (d, k),
                             jnp.float32) * 0.01
    params = {"w": init, "b": jnp.zeros((k,), jnp.float32)}
    base_key = jax.random.PRNGKey(seed + 1)
    curve = []
    with obs.span("scenario.cell", **{k_: str(v)
                                      for k_, v in cell.items()}):
        for r in range(nr_rounds):
            new = round_fn(params, base_key, r)
            if gate is not None:
                new, _ = gate.admit(r, params, new)
            params = new
            curve.append(float(accuracy(params)))
    result = {
        "cell": cell,
        "final_accuracy": curve[-1],
        "best_accuracy": max(curve),
        "round_accuracy": curve,
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    if gate is not None:
        result["val_gate"] = {"policy": val_gate,
                              "rejections": gate.events}
    if secagg is not None:
        result["secagg_stats"] = dict(secagg.stats)
        result["secagg_groups"] = secagg.nr_groups
    return result


def build_cells(attacks, aggregators, modes, cohorts,
                attack_fraction: float) -> list[dict]:
    return [
        {"attack": a, "aggregator": g, "mode": m, "cohort": c,
         "attack_fraction": attack_fraction}
        for a in attacks for g in aggregators for m in modes
        for c in cohorts
    ]


def cell_name(cell: dict) -> str:
    return (f"{cell['attack']}_{cell['aggregator']}_{cell['mode']}"
            f"_c{cell['cohort']}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="attack x defense scenario matrix over the FL engine")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 matrix: sign-flip x {mean, median} x "
                         "{plain, secagg} at one tiny cohort")
    ap.add_argument("--cohorts", default="8,32",
                    help="comma-separated cohort sizes (e.g. 8,32,1024)")
    ap.add_argument("--attack-fraction", type=float, default=0.3)
    ap.add_argument("--nr-rounds", type=int, default=30)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", type=Path,
                    default=Path("results/scenario_matrix"))
    ap.add_argument("--val-gate", default="restore",
                    choices=("", "skip", "clip", "restore"),
                    help="holdout validation-gate policy applied to every "
                         "cell ('' disables the gate)")
    ap.add_argument("--telemetry", default=None,
                    help="obs telemetry JSONL path (tools/obs_report.py "
                         "renders the attacks & defenses section from it)")
    args = ap.parse_args(argv)

    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ddl25spring_tpu import obs

    if args.telemetry:
        obs.enable(args.telemetry)

    if args.smoke:
        cells = build_cells(("sign-flip",), ("mean", "median"),
                            ("plain", "secagg"), (8,),
                            args.attack_fraction)
    else:
        cohorts = tuple(int(c) for c in args.cohorts.split(","))
        cells = build_cells(ATTACKS, AGGREGATORS, MODES, cohorts,
                            args.attack_fraction)

    args.out.mkdir(parents=True, exist_ok=True)
    rows = []
    for cell in cells:
        res = run_cell(cell, args.nr_rounds, args.seed,
                       val_gate=args.val_gate)
        rows.append(res)
        path = args.out / f"{cell_name(cell)}.json"
        path.write_text(json.dumps(res, indent=2) + "\n")
        if "skipped" in res:
            print(f"[skip] {cell_name(cell)}: {res['skipped']}")
        else:
            print(f"[cell] {cell_name(cell)}: "
                  f"final={res['final_accuracy']:.1f}% "
                  f"best={res['best_accuracy']:.1f}% "
                  f"({res['wall_s']}s)")

    summary = {
        "nr_rounds": args.nr_rounds,
        "attack_fraction": args.attack_fraction,
        "seed": args.seed,
        "cells": [
            {**({"final_accuracy": r.get("final_accuracy")}
                if "skipped" not in r else {"skipped": r["skipped"]}),
             "name": cell_name(r["cell"])}
            for r in rows
        ],
    }
    (args.out / "summary.json").write_text(
        json.dumps(summary, indent=2) + "\n")
    print(f"wrote {len(rows)} cell files + summary.json to {args.out}")
    obs.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
