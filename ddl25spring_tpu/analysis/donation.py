"""donation-safety pass: reads of a donated buffer after the donating
call — the exact shape of the PR 4 miscompile.

When a jitted function donates an argument (``donate_argnums`` /
``donate_argnames``), the caller's buffer is dead the moment the call
dispatches; reading it afterwards returns whatever the executable left in
the aliased memory.  jax warns at runtime only when the read *happens*,
and the PR 4 bug (persistent-cache-deserialized executables reordering
donated-buffer scatters) showed the read can even be inside the compiled
program.  Statically:

1. collect *donating callables* per module — names bound to
   ``jax.jit(f, donate_argnums=...)`` and functions decorated with a
   donating jit.  Donated positions are every int literal inside the
   ``donate_argnums`` expression, so conditional shapes
   (``(0, 1) if donate else ()``) and wrappers (``donation_safe((0,))``)
   count as "may donate" — the safe direction;
2. scan every scope linearly: a ``Name`` passed at a donated position
   becomes *dead* after the call statement; any later read of a dead name
   in that scope is ``DON001``.  Rebinding (including the idiomatic
   ``params = step(params)``) revives the name.

Loop back-edges are not modeled (a read-before-rebind inside a loop body
is caught only in source order) — the straight-line shape is the one that
shipped a bug.
"""

from __future__ import annotations

import ast

from .core import Finding, ProjectIndex, int_literals, terminal_name

PASS_ID = "donation-safety"

JIT_NAMES = {"jit", "pjit"}
DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


def _donation_spec(keywords) -> tuple[set[int], set[str]] | None:
    """Donated positions/names from a jit call's keywords, or None when
    nothing (statically) donates."""
    nums: set[int] = set()
    names: set[str] = set()
    for kw in keywords:
        if kw.arg == "donate_argnums":
            nums |= int_literals(kw.value)
        elif kw.arg == "donate_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    if nums or names:
        return nums, names
    return None


def _jit_call_spec(node: ast.Call):
    """(is_jit_call, donation_spec) for ``jax.jit(...)`` call exprs."""
    t = terminal_name(node.func)
    if t in JIT_NAMES:
        return True, _donation_spec(node.keywords)
    if t == "partial" and node.args \
            and terminal_name(node.args[0]) in JIT_NAMES:
        return True, _donation_spec(node.keywords)
    return False, None


class _ScopeScanner:
    """Linear scan of one scope's statements tracking dead (donated)
    names."""

    def __init__(self, mi, scope_name: str, donors: dict,
                 findings: list[Finding]):
        self.mi = mi
        self.scope_name = scope_name
        self.donors = donors            # name -> (positions, kwnames)
        self.findings = findings
        self.dead: dict[str, int] = {}  # name -> donating call lineno

    def flag(self, node, name, call_line):
        self.findings.append(Finding(
            pass_id=PASS_ID, rule="DON001", path=self.mi.rel,
            line=getattr(node, "lineno", 0),
            scope=f"{self.mi.name or self.mi.rel}:{self.scope_name}"
            if self.scope_name else (self.mi.name or self.mi.rel),
            message=(f"`{name}` was donated to a jitted call at line "
                     f"{call_line} and read afterwards — the buffer is "
                     "dead (PR 4 shape: donated-buffer aliasing)"),
            detail=name,
        ))

    def check_reads(self, expr: ast.AST, skip: set[int] = frozenset()):
        for n in ast.walk(expr):
            if id(n) in skip:
                continue
            if isinstance(n, ast.Name) \
                    and isinstance(n.ctx, ast.Load) \
                    and n.id in self.dead:
                self.flag(n, n.id, self.dead[n.id])

    def donating_calls(self, expr: ast.AST):
        """(call node, donated Name args) for calls to known donors."""
        out = []
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            t = terminal_name(n.func)
            spec = self.donors.get(t)
            if spec is None:
                continue
            positions, kwnames = spec
            donated: list[str] = []
            for i, a in enumerate(n.args):
                if i in positions and isinstance(a, ast.Name):
                    donated.append(a.id)
            for kw in n.keywords:
                if kw.arg in kwnames and isinstance(kw.value, ast.Name):
                    donated.append(kw.value.id)
            if donated:
                out.append((n, donated))
        return out

    def revive(self, target: ast.AST):
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                self.dead.pop(n.id, None)

    def exec_stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return  # inner scopes scanned separately
        exprs = [v for v in (getattr(s, "value", None),
                             getattr(s, "test", None),
                             getattr(s, "iter", None),
                             getattr(s, "exc", None)) if v is not None]
        if isinstance(s, ast.With):
            exprs.extend(i.context_expr for i in s.items)
        for e in exprs:
            self.check_reads(e)
            for call, donated in self.donating_calls(e):
                for name in donated:
                    self.dead[name] = call.lineno
        if isinstance(s, (ast.Assign, ast.AnnAssign)):
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            for t in targets:
                self.revive(t)
        elif isinstance(s, ast.AugAssign):
            # x += f(...) reads x first — already covered by check_reads
            self.revive(s.target)
        for fld in ("body", "orelse", "finalbody"):
            for child in getattr(s, fld, ()):
                self.exec_stmt(child)
        for h in getattr(s, "handlers", ()):
            for child in h.body:
                self.exec_stmt(child)

    def run(self, body):
        for s in body:
            self.exec_stmt(s)


def _collect_donors(tree: ast.Module) -> dict:
    """All names that (may) donate when called: jit-wrapped assignments
    and donating-jit-decorated defs, collected module-wide (closures call
    donors bound in enclosing scopes, so one flat namespace is the
    pragmatic approximation)."""
    donors: dict[str, tuple[set[int], set[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            is_jit, spec = _jit_call_spec(node.value)
            if is_jit and spec is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donors[t.id] = spec
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    is_jit, spec = _jit_call_spec(dec)
                    if is_jit and spec is not None:
                        donors[node.name] = spec
    return donors


def run(idx: ProjectIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mi in idx.files:
        donors = _collect_donors(mi.tree)
        if not donors:
            continue
        # module scope + every function scope, each scanned linearly
        _ScopeScanner(mi, "", donors, findings).run(
            [s for s in mi.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.ClassDef))])
        for node in ast.walk(mi.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _ScopeScanner(mi, node.name, donors, findings).run(
                    node.body)
    return findings
