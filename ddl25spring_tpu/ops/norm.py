"""Bandwidth-lean GroupNorm for bf16 models.

Flax's ``nn.GroupNorm`` promotes the whole elementwise chain to float32
(stats AND ``(x - mean) * rsqrt(var + eps) * scale + bias``), casting back to
the compute dtype only at the end.  On TPU the north-star ResNet is
HBM-bandwidth-bound around its norms (docs/BENCHMARKS.md roofline), and an
f32 elementwise chain doubles the bytes of every non-fused intermediate.

This variant keeps the float32 where it matters — the mean/variance
*reductions* — and runs the elementwise normalisation in the storage dtype
(bf16 in the bench config): per-group ``mean`` and ``rsqrt(var+eps)`` are
O(groups) scalars, so folding them with scale/bias in f32 costs nothing,
and only the final fused-multiply-add touches the (N, H, W, C) tensor, in
bf16.  Numerics: identical reductions; the elementwise rounding differs from
flax by ~1 bf16 ulp (pinned in ``tests/test_models.py``).

Selectable via ``ResNet(norm_impl="lean")``.  The A/B landed on round-4
hardware: 3.90 rounds/sec vs flax's 1.55 on the north star at
equal-or-better final accuracy (results/bench_tpu_lean.json), so
``bench.py`` now defaults to lean; the flax path remains for the A/B and
for f32 teaching runs.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


class LeanGroupNorm(nn.Module):
    """GroupNorm over the trailing channel axis of an NHWC tensor."""

    num_groups: int
    epsilon: float = 1e-6
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        *lead, c = x.shape
        g = self.num_groups
        if c % g:
            raise ValueError(f"channels {c} not divisible by groups {g}")
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)

        # f32 reductions over (spatial..., channels-in-group); operand stays
        # in storage dtype, accumulation dtype is forced up
        xg = x.reshape(x.shape[0], -1, g, c // g)
        red = (1, 3)
        mean = jnp.mean(xg, axis=red, dtype=jnp.float32)         # (N, g)
        mean2 = jnp.mean(
            lax.square(xg.astype(jnp.float32)), axis=red
        )
        var = jnp.maximum(mean2 - lax.square(mean), 0.0)
        inv = lax.rsqrt(var + self.epsilon)                      # (N, g)

        # fold per-group stats with per-channel affine in f32 (O(N*g + c)),
        # then ONE bf16 fused multiply-add over the big tensor
        inv_c = jnp.repeat(inv, c // g, axis=-1)                 # (N, c)
        mean_c = jnp.repeat(mean, c // g, axis=-1)
        mul = (inv_c * scale[None, :]).astype(self.dtype)        # (N, c)
        add = (bias[None, :] - mean_c * inv_c * scale[None, :]).astype(
            self.dtype
        )
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (c,)
        return x.astype(self.dtype) * mul.reshape(shape) + add.reshape(shape)
