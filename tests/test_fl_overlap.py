"""Overlapped chunked combine + host-feed prefetch: hidden, not changed.

``overlap_combine=True`` replaces the sharded round's single end-of-round
``psum`` with per-chunk ring reduce-scatter/all-gather partial combines
(``fl/sharding.py ring_all_reduce``) interleaved into the client chunk
scan — the combine cost rides UNDER the next chunk's compute.  The
contract mirrors the sharding oracle (tests/test_fl_sharded.py):

- ``overlap_combine`` at shard count 1 is BIT-identical to overlap off
  (the W=1 ring is the identity);
- W > 1 float paths agree with overlap-off to float-sum-reorder
  tolerance, and the ring result is SHARD-INDEPENDENT (every shard holds
  the same bits — the per-chunk partial combine must not reintroduce
  per-shard summation orders under the replicated out_specs);
- secagg's uint32 modular sums are order-independent, so overlapped
  rounds stay BITWISE identical to local at every world size.

``prefetch_depth > 0`` moves cohort batch assembly onto a host producer
thread (data/prefetch.py) that device_puts round r+1's rows while round
r runs.  Sampling stays device-side and draw-order identical, so params
are BIT-identical to the synchronous path at any depth.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.data.prefetch import PrefetchStream
from ddl25spring_tpu.data.split import ClientDatasets
from ddl25spring_tpu.fl.engine import make_fl_round, make_local_sgd_update
from ddl25spring_tpu.fl.fedbuff import init_history, make_fedbuff_round
from ddl25spring_tpu.fl.sharding import ring_all_reduce
from ddl25spring_tpu.fl.task import Task
from ddl25spring_tpu.parallel import make_mesh
from ddl25spring_tpu.resilience.faults import FaultPlan
from ddl25spring_tpu.secagg.protocol import SecAgg

# same tiny logistic-regression geometry as tests/test_fl_sharded.py
N, PER, D, K, BS = 12, 16, 8, 4, 8
NR_SAMPLED = 8
_rng = np.random.default_rng(42)
X = _rng.normal(size=(N, PER, D)).astype(np.float32)
Y = _rng.integers(0, K, size=(N, PER)).astype(np.int32)
COUNTS = np.full((N,), PER, np.int32)
COUNTS[0] = PER - 3
COUNTS[5] = PER - 5

P0 = {"w": jnp.zeros((D, K), jnp.float32),
      "b": jnp.zeros((K,), jnp.float32)}
KEY = jax.random.PRNGKey(3)


def loss_fn(params, xb, yb, mask, key):
    logits = xb @ params["w"] + params["b"]
    ls = -jax.nn.log_softmax(logits)[jnp.arange(yb.shape[0]), yb]
    return jnp.sum(ls * mask) / jnp.maximum(jnp.sum(mask), 1)


UPDATE = make_local_sgd_update(loss_fn, 0.05, BS, 1)


def clients_mesh(w):
    return make_mesh({"clients": w}, devices=jax.devices()[:w])


def build(mesh=None, **kw):
    return make_fl_round(UPDATE, X, Y, COUNTS, NR_SAMPLED,
                         device_put_data=False, mesh=mesh, **kw)


def run_rounds(rf, nr=3, p0=P0):
    p = p0
    for r in range(nr):
        p = rf(p, KEY, r)
    return p


def max_err(a, b):
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def trees_bitwise(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# --- ring all-reduce primitive ---------------------------------------------


@pytest.mark.parametrize("world", [1, 2, 4, 8])
def test_ring_all_reduce_matches_psum(world):
    """RS+AG == psum to float tolerance, and the result is the SAME BITS
    on every shard (the property the overlap correctness rests on)."""
    from jax.sharding import PartitionSpec as P

    from ddl25spring_tpu.parallel.compat import shard_map

    mesh = clients_mesh(world)
    rng = np.random.default_rng(0)
    tree = {
        "a": jnp.asarray(rng.normal(size=(world, 5, 3)), jnp.float32),
        "s": jnp.asarray(rng.normal(size=(world,)), jnp.float32),
        "u": jnp.asarray(
            rng.integers(0, 2**32, size=(world, 7), dtype=np.uint32)),
    }

    def body(t):
        ring = ring_all_reduce(t, "clients", world=world)
        ps = jax.tree.map(
            lambda l: jax.lax.psum(l, "clients"), t)
        return ring, ps

    ring, ps = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("clients"), tree),),
        out_specs=(jax.tree.map(lambda _: P("clients"), tree),) * 2,
        check_vma=False,
    ))(tree)
    # every shard's copy identical -> comparing the stacked (W, ...) axes
    for name, leaf in ring.items():
        per_shard = np.asarray(leaf).reshape((world, -1))
        assert (per_shard == per_shard[0]).all(), name
    # uint32 modular sums are order-independent: exactly psum's bits
    assert np.array_equal(np.asarray(ring["u"]), np.asarray(ps["u"]))
    if world == 1:
        assert trees_bitwise(ring, ps)
    else:
        assert max_err(
            {k: ring[k] for k in ("a", "s")},
            {k: ps[k] for k in ("a", "s")}) < 1e-5


# --- engine: overlapped rounds == plain rounds -----------------------------


@pytest.mark.parametrize("chunk", [0, 4], ids=["stacked", "chunk4"])
@pytest.mark.parametrize("world", [1, 2, 4])
def test_overlap_matches_plain_sharded(world, chunk):
    rf_off = build(mesh=clients_mesh(world), client_chunk=chunk)
    rf_on = build(mesh=clients_mesh(world), client_chunk=chunk,
                  overlap_combine=True)
    assert rf_on.overlap
    p_off = run_rounds(rf_off)
    p_on = run_rounds(rf_on)
    err = max_err(p_off, p_on)
    if world == 1:
        # the W=1 ring is the identity: overlap changes NOTHING
        assert err == 0.0
    else:
        assert err < 1e-6
    # and both still track the local oracle
    assert max_err(run_rounds(build(client_chunk=chunk)), p_on) < 1e-6


def test_overlap_without_mesh_is_inert():
    rf = build(overlap_combine=True)
    assert not rf.overlap
    assert trees_bitwise(run_rounds(rf), run_rounds(build()))


@pytest.mark.parametrize("world", [2, 4])
def test_overlap_fault_stats_order_exact(world):
    plan = FaultPlan(seed=7, drop=0.2, nan=0.1)
    rf_off = build(mesh=clients_mesh(world), fault_plan=plan,
                   round_deadline_s=1.0)
    rf_on = build(mesh=clients_mesh(world), fault_plan=plan,
                  round_deadline_s=1.0, overlap_combine=True)
    for r in range(2):
        p_off, s_off = rf_off.raw(P0, KEY, r, *rf_off.data)
        p_on, s_on = rf_on.raw(P0, KEY, r, *rf_on.data)
        # int32 stats ride the same ring: order-exact, so EXACTLY equal
        assert np.array_equal(np.asarray(s_off), np.asarray(s_on))
        assert max_err(p_off, p_on) < 1e-6


# --- secagg: modular sums are order-independent -> bitwise at any W --------


@pytest.mark.parametrize("world", [1, 2, 4])
def test_overlap_secagg_bitwise(world):
    def secagg_round(mesh, **kw):
        sa = SecAgg(N, NR_SAMPLED, counts=np.asarray(COUNTS), clip=4.0,
                    seed=3)
        return make_fl_round(UPDATE, X, Y, COUNTS, NR_SAMPLED, mesh=mesh,
                             device_put_data=False, secagg=sa,
                             fault_plan=FaultPlan(seed=7, drop=0.2),
                             round_deadline_s=1.0, **kw)

    rf_local = secagg_round(None)
    rf_on = secagg_round(clients_mesh(world), overlap_combine=True)
    assert rf_on.overlap == (world >= 1)
    f_l, p_l, s_l = rf_local.secagg_oracle(P0, KEY, 1)
    f_s, p_s, s_s = rf_on.secagg_oracle(P0, KEY, 1)
    assert trees_bitwise(f_l, f_s), "masked field sums diverged"
    assert trees_bitwise(p_l, p_s), "plaintext field sums diverged"
    assert np.array_equal(np.asarray(s_l), np.asarray(s_s))
    # whole rounds: pure function of the modular sum -> still bitwise
    assert max_err(secagg_round(None)(P0, KEY, 0),
                   secagg_round(clients_mesh(world),
                                overlap_combine=True)(P0, KEY, 0)) == 0.0


# --- fedbuff ---------------------------------------------------------------


@pytest.mark.parametrize("chunk", [0, 4], ids=["plain", "chunk4"])
@pytest.mark.parametrize("world", [1, 4])
def test_fedbuff_overlap_matches_plain(world, chunk):
    def tick(mesh, **kw):
        return make_fedbuff_round(UPDATE, X, Y, COUNTS, NR_SAMPLED,
                                  staleness_window=3,
                                  fault_plan=FaultPlan(seed=7, drop=0.2),
                                  round_deadline_s=1.0, mesh=mesh, **kw)

    tk_off = tick(clients_mesh(world), client_chunk=chunk)
    tk_on = tick(clients_mesh(world), client_chunk=chunk,
                 overlap_combine=True)
    assert tk_on.overlap
    h_off = init_history(P0, 3)
    h_on = init_history(P0, 3)
    for r in range(3):
        h_off = tk_off(h_off, KEY, r)
        h_on = tk_on(h_on, KEY, r)
    err = max_err(h_off, h_on)
    if world == 1:
        assert err == 0.0
    else:
        assert err < 1e-6


# --- host-feed prefetch: bit-identical at any depth ------------------------


@pytest.mark.parametrize("chunk", [0, 4], ids=["stacked", "chunk4"])
@pytest.mark.parametrize("depth", [1, 2])
def test_prefetch_bit_identical(depth, chunk):
    rf_sync = build(client_chunk=chunk)
    rf_feed = build(client_chunk=chunk, prefetch_depth=depth)
    assert rf_feed.prefetch_depth == depth
    assert rf_sync.prefetch_depth == 0
    assert trees_bitwise(run_rounds(rf_sync), run_rounds(rf_feed))


def test_prefetch_with_sharded_and_overlap_bit_identical():
    mesh = clients_mesh(4)
    want = run_rounds(build(mesh=mesh, client_chunk=4))
    got = run_rounds(build(mesh=mesh, client_chunk=4, prefetch_depth=2))
    assert trees_bitwise(want, got)
    both = run_rounds(build(mesh=mesh, client_chunk=4, prefetch_depth=2,
                            overlap_combine=True))
    assert max_err(want, both) < 1e-6


def test_prefetch_host_cohort_oracle():
    """The host-side replay draws the SAME cohort the device program
    samples — the property the whole feed path's bit-identity rests on
    — and is deterministic per (key, round)."""
    rf = build(prefetch_depth=1)
    a = rf.host_cohort(KEY, 0)
    b = rf.host_cohort(KEY, 0)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (NR_SAMPLED,)
    assert ((a >= 0) & (a < N)).all()
    # distinct rounds draw distinct cohorts (fold_in separation)
    assert not np.array_equal(a, rf.host_cohort(KEY, 1))
    # synchronous rounds have no host replay to drift
    assert build().host_cohort is None


def test_prefetch_validation_and_trace_guard():
    with pytest.raises(ValueError, match="prefetch_depth"):
        build(prefetch_depth=-1)
    rf = build(prefetch_depth=1)
    with pytest.raises(RuntimeError, match="prefetch"):
        jax.jit(rf)(P0, KEY, 0)


# --- prefetch stream: producer death must not deadlock ---------------------


class _DyingSource:
    def __init__(self, yield_n):
        self.yield_n = yield_n
        self.n = 0

    def next_batch(self):
        if self.n >= self.yield_n:
            raise RuntimeError("boom")
        self.n += 1
        return self.n


def test_prefetch_stream_relays_producer_error():
    s = PrefetchStream(_DyingSource(2), depth=4)
    assert s.next_batch() == 1
    assert next(s) == 2  # __next__ alias shares the error discipline
    with pytest.raises(RuntimeError, match="boom"):
        s.next_batch()
    s.close()


def test_prefetch_stream_producer_death_with_full_queue_no_deadlock():
    """Regression: a producer that raises while the queue is FULL used to
    spin forever trying to enqueue the error sentinel; the consumer then
    waited on a queue that never drained.  The error is sticky now — the
    consumer must surface it even if the sentinel never fit."""
    s = PrefetchStream(_DyingSource(1), depth=1)
    # let the producer fill the queue, raise, and exhaust its bounded
    # error-put window (20 x 0.1 s)
    deadline = time.monotonic() + 10
    while s._thread.is_alive() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not s._thread.is_alive(), "producer must exit, not spin"
    got = []
    done = threading.Event()

    def consume():
        got.append(s.next_batch())       # the one real batch
        try:
            s.next_batch()
        except RuntimeError as e:
            got.append(str(e))
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert done.wait(10), "consumer deadlocked on dead producer"
    assert got[0] == 1 and "boom" in got[1]
    s.close()


# --- tools/mem_estimate.py --overlap tier-1 smoke --------------------------


def test_mem_estimate_overlap_cell():
    """The --overlap AOT cell compiles both rounds and holds its claims:
    W=1 overlap is program-identical (the ring is the identity, same
    temp bytes), W>1 stays within the 2x temp-bytes bound the cell
    asserts internally, and the ppermute wire signature is the ring's
    2*(W-1)/W volume."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "mem_estimate",
        Path(__file__).resolve().parent.parent / "tools" / "mem_estimate.py",
    )
    me = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(me)

    out = me.overlap_estimate(16, 8, 2, [1, 2])
    cells = {c["world"]: c for c in out["cells"]}
    assert set(cells) == {1, 2}
    w1 = cells[1]
    assert w1["nr_ppermutes"] == 0 and w1["ppermute_wire_bytes"] == 0
    assert w1["temp_bytes_overlap"] == w1["temp_bytes_plain"]
    w2 = cells[2]
    # 2 leaves x 2*(W-1) steps x nr_combines(=2 chunks of 2 in a 4-row
    # shard) ppermutes, each step moving payload/W bytes
    assert w2["nr_ppermutes"] == 8
    assert w2["ppermute_wire_bytes"] > 0
    assert 0 < w2["temp_bytes_overlap"] <= 2 * w2["temp_bytes_plain"] + (
        1 << 20)


# --- all five servers: overlapped combine == plain at every world ----------


def _tiny_task():
    return Task(
        init=lambda key: {"w": jnp.zeros((D, K), jnp.float32),
                          "b": jnp.zeros((K,), jnp.float32)},
        loss_fn=loss_fn,
        score_fn=lambda params, x: x @ params["w"] + params["b"],
        test_x=X[0], test_y=Y[0],
    )


CD = ClientDatasets(x=X, y=Y, counts=COUNTS)
FRACTION = NR_SAMPLED / N


def _fedsgd_grad(mesh, overlap):
    from ddl25spring_tpu.fl.servers import FedSgdGradientServer

    return FedSgdGradientServer(
        _tiny_task(), lr=0.05, client_data=CD, client_fraction=FRACTION,
        seed=0, mesh=mesh, overlap_combine=overlap)


def _fedsgd_weight(mesh, overlap):
    from ddl25spring_tpu.fl.servers import FedSgdWeightServer

    return FedSgdWeightServer(
        _tiny_task(), lr=0.05, client_data=CD, client_fraction=FRACTION,
        seed=0, mesh=mesh, overlap_combine=overlap)


def _fedavg(mesh, overlap):
    from ddl25spring_tpu.fl.servers import FedAvgServer

    return FedAvgServer(
        _tiny_task(), lr=0.05, batch_size=BS, client_data=CD,
        client_fraction=FRACTION, nr_local_epochs=2, seed=0, mesh=mesh,
        overlap_combine=overlap)


def _fedopt(mesh, overlap):
    from ddl25spring_tpu.fl.servers import FedOptServer

    return FedOptServer(
        _tiny_task(), lr=0.05, batch_size=BS, client_data=CD,
        client_fraction=FRACTION, nr_local_epochs=1, seed=0,
        server_optimizer="adam", server_lr=0.01, mesh=mesh,
        overlap_combine=overlap)


def _fedbuff(mesh, overlap):
    from ddl25spring_tpu.fl.fedbuff import FedBuffServer

    return FedBuffServer(
        _tiny_task(), lr=0.05, batch_size=BS, client_data=CD,
        client_fraction=FRACTION, nr_local_epochs=1, seed=0,
        staleness_window=2, mesh=mesh, overlap_combine=overlap)


@pytest.mark.parametrize("build_server", [
    _fedsgd_grad, _fedsgd_weight, _fedavg, _fedopt, _fedbuff,
], ids=["fedsgd_grad", "fedsgd_weight", "fedavg", "fedopt", "fedbuff"])
@pytest.mark.parametrize("world", [1, 4])
def test_server_overlap_matches_plain(build_server, world):
    """Every server's overlapped round tracks its plain sharded round:
    bit-identical at W=1 (the singleton ring is the identity), float
    summation-order tolerance at W=4 — including cross-round server
    state (FedOpt moments, FedBuff history)."""
    mesh = clients_mesh(world)
    plain, over = build_server(mesh, False), build_server(mesh, True)
    p_p, p_o = plain.params, over.params
    for r in range(2):
        p_p = plain.round_fn(p_p, plain.run_key, r)
        p_o = over.round_fn(p_o, over.run_key, r)
    err = max_err(p_p, p_o)
    if world == 1:
        assert err == 0.0
    else:
        assert err < 1e-6
    for key, val in plain.extra_state().items():
        assert max_err(val, over.extra_state()[key]) < 1e-6
