from .cnn import MnistCnn
from .llama import (
    Llama,
    LlamaConfig,
    LlamaFirstStage,
    LlamaMidStage,
    LlamaLastStage,
    make_stages,
    split_stage_layers,
    full_params_to_stage_params,
)

__all__ = [
    "MnistCnn",
    "Llama",
    "LlamaConfig",
    "LlamaFirstStage",
    "LlamaMidStage",
    "LlamaLastStage",
    "make_stages",
    "split_stage_layers",
    "full_params_to_stage_params",
]
