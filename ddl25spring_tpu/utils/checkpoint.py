"""Checkpoint / resume.

The reference has no persistence at all: its only "checkpoint" is an
in-memory best-weights restore (lab/tutorial_2a/centralized.py:51,67-70), and
a crashed run restarts from zero.  Here any training pytree — params,
optimizer state, round/step counter — is saved atomically via orbax (the
standard JAX checkpoint layer) and restored with sharding preserved, so a
multi-chip run resumes onto the same mesh layout.
"""

from __future__ import annotations

import os
from typing import Any

import jax


class Checkpointer:
    """Thin orbax CheckpointManager wrapper: numbered steps, keep-N,
    atomic writes.

    ``state`` can be any pytree of arrays/scalars (e.g. ``{"params": ...,
    "opt_state": ..., "round": r}``).  ``restore`` needs a ``template`` pytree
    of matching structure (typically the freshly initialised state) so orbax
    can rebuild dtypes/shardings.
    """

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(os.fspath(directory)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        """``wait=False`` makes the save asynchronous: orbax snapshots the
        arrays and writes in a background thread while training continues
        (the next ``save``/``restore``/``close`` synchronises first, so
        checkpoints can never interleave — orbax only drains on save/close
        itself; ``restore`` drains explicitly below).  The training CLIs
        save async and sync at close — a checkpoint write costs the round
        that issues it nothing but the host snapshot."""
        self._mngr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, template: Any, step: int | None = None) -> Any:
        # drain any in-flight async save first: orbax's restore does NOT
        # (verified, 0.11.x) — without this, latest_step() skips the
        # still-uncommitted newest step and silently restores stale state
        self._mngr.wait_until_finished()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        abstract = jax.tree.map(
            lambda x: x if not hasattr(x, "shape")
            else jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=_sharding(x)),
            template,
        )
        return uncommit_restored(self._mngr.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        ))

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self):
        return self._mngr.all_steps()

    def close(self):
        # orbax >= 0.11 drains in-flight async saves in close() itself, but
        # the declared dependency floor is older — drain explicitly (no-op
        # when orbax already does it) so the newest checkpoint can never be
        # dropped on any supported version
        self._mngr.wait_until_finished()
        self._mngr.close()


def _sharding(x):
    return getattr(x, "sharding", None)


def uncommit_restored(tree):
    """Strip device commitment from single-device restored arrays (applied by
    ``Checkpointer.restore`` to everything it returns).

    Orbax restores an unsharded template leaf COMMITTED to one device; a
    later jit then refuses to mix it with mesh-sharded inputs ("incompatible
    devices").  Freshly-initialised params are uncommitted (jit replicates
    them freely across a mesh), so resumed state must be too.  Mesh-sharded
    leaves (pipeline stages, TP shards, ZeRO slices — restored with their
    sharding preserved) span several devices and pass through untouched."""
    import jax.numpy as jnp
    import numpy as np

    def fix(a):
        if isinstance(a, jax.Array) and len(a.devices()) == 1:
            return jnp.asarray(np.asarray(a))
        return a

    return jax.tree.map(fix, tree)
