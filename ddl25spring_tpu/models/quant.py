"""Weight-only int8 quantization for LLaMA inference.

The reference has no inference path at all (SURVEY.md: training loss is its
only output); this framework's generation stack gains the standard serving
compression: matmul kernels stored as int8 with per-output-channel float
scales, dequantized INSIDE the matmul consumer — XLA fuses the
``int8 -> compute-dtype cast * scale`` into the weight load, so HBM holds
(and the decode step streams) one byte per weight instead of four.  On a
bandwidth-bound decode step, weight bytes are the bill; everything else
(activations, KV cache) is unchanged.

Scope: the seven transformer matmuls (wq/wk/wv/wo, w1/w2/w3) and the LM
head.  Embeddings and norm scales stay float — they are small, and the
embedding gather's output feeds layernorm-sensitive math.

Usage::

    qparams = quantize_llama_params(params)          # trained fp params in
    qcfg = dataclasses.replace(cfg, weights_int8=True)
    out = generate(qcfg, qparams, prompt, n)         # same API

Per-channel absmax symmetric quantization: ``w ≈ q * scale`` with
``scale = max|w_col| / 127``; worst-case per-weight error is scale/2, i.e.
<= 0.4% of the channel's largest weight.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

QUANT_KERNELS = ("wq", "wk", "wv", "wo", "w1", "w2", "w3", "lm_head")


class QuantDense(nn.Module):
    """Dense layer over int8 weights + per-output-channel f32 scales.

    Parameters are ``kernel_q`` (in, out) int8 and ``scale`` (out,) f32 —
    produced by :func:`quantize_llama_params` from a trained ``nn.Dense``
    kernel; the init values only size the tree."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kq = self.param(
            "kernel_q", nn.initializers.zeros,
            (x.shape[-1], self.features), jnp.int8,
        )
        scale = self.param(
            "scale", nn.initializers.ones, (self.features,), jnp.float32
        )
        # dequant fuses into the matmul's weight read: int8 resident in HBM
        w = kq.astype(self.dtype) * scale.astype(self.dtype)[None, :]
        return jnp.dot(x.astype(self.dtype), w)


def quantize_llama_params(params):
    """fp param tree -> the matching ``weights_int8=True`` param tree.

    Kernels named in ``QUANT_KERNELS`` become ``{kernel_q, scale}``
    (per-output-channel absmax); everything else passes through unchanged.
    """

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if name in QUANT_KERNELS and isinstance(sub, dict) \
                    and "kernel" in sub:
                w = jnp.asarray(sub["kernel"], jnp.float32)
                if w.ndim != 2:
                    # name matching alone is too loose a key: a future tree
                    # reusing one of these names for a non-matmul param
                    # must fail here, not load garbage into QuantDense
                    raise ValueError(
                        f"quantize_llama_params: param {name!r} has shape "
                        f"{w.shape}; expected a 2-D matmul kernel — the "
                        f"tree does not look like a Llama param tree"
                    )
                scale = jnp.maximum(
                    jnp.max(jnp.abs(w), axis=0), 1e-8
                ) / 127.0
                q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127)
                out[name] = {
                    "kernel_q": q.astype(jnp.int8),
                    "scale": scale,
                }
            elif isinstance(sub, dict):
                out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    return {k: walk(v) for k, v in params.items()}
