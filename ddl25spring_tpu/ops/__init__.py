from .losses import (
    nll_loss,
    cross_entropy_logits,
    causal_lm_loss,
    accuracy,
)
from .attention import causal_attention, ring_causal_attention

# The Pallas ops resolve lazily (PEP 562) so `from ddl25spring_tpu.ops
# import causal_lm_loss` — every FL/data path — doesn't pull
# jax.experimental.pallas into processes that never touch a kernel.
_LAZY = {
    "flash_causal_attention": "flash_attention",
    "flash_block_attention": "flash_attention",
    "ring_flash_causal_attention": "ring_flash",
    "pairwise_sq_dists": "pairwise",
    "dist_pass_bytes": "pairwise",
    "row_norms": "pairwise",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "nll_loss",
    "cross_entropy_logits",
    "causal_lm_loss",
    "accuracy",
    "causal_attention",
    "ring_causal_attention",
    "flash_causal_attention",
    "flash_block_attention",
    "ring_flash_causal_attention",
    "pairwise_sq_dists",
    "dist_pass_bytes",
    "row_norms",
]
