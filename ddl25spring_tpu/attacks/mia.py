"""Membership inference attacks (MIA) — classifiers and generative models.

The generative half is the course's Part-3 headline ("Attacks & Defenses in
Generative Models", lab/README.md:13-16): a VAE trained on a small private
table (the reference's Autoencoder on heart.csv,
generative-modeling.py:133-165) memorizes — records it trained on
reconstruct with lower error than records it never saw.  An attacker holding
the model and a candidate record scores membership by reconstruction error.

- :func:`loss_scores` — per-record loss of a classifier; Yeom et al. 2018's
  threshold attack uses it directly (members have lower loss on an
  overfitted model).
- :func:`vae_reconstruction_scores` — per-record deterministic ELBO-style
  score of a :class:`~ddl25spring_tpu.models.vae.TabularVAE`: mean-path
  reconstruction MSE plus the KL term (both per record, no sampling noise).
- :func:`attack_auc` — the Mann-Whitney AUC of "score separates members
  from non-members"; 0.5 = no leak, 1.0 = total leak.  This is the number a
  defense (DP noise, early stopping, more data) must push toward 0.5.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def loss_scores(log_probs, labels) -> jnp.ndarray:
    """Per-record NLL (no reduction) — lower = more member-like."""
    return -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]


def vae_reconstruction_scores(
    vae, variables, x, *, include_kl: bool = True
) -> jnp.ndarray:
    """Per-record deterministic VAE score: ``||x - dec(mu(x))||² +
    KL(q(z|x) || N(0, I))``; lower = more member-like.

    Eval-mode apply (running BatchNorm stats, mean-path latent) so the score
    is a pure function of the record — the attacker needs no RNG luck.
    """
    recon, mu, logvar = vae.apply(variables, x, train=False)
    mse = jnp.sum(jnp.square(recon - x), axis=-1)
    if not include_kl:
        return mse
    kl = -0.5 * jnp.sum(
        1 + logvar - jnp.square(mu) - jnp.exp(logvar), axis=-1
    )
    return mse + kl


def attack_auc(member_scores, nonmember_scores) -> float:
    """AUC of the rule "lower score ⇒ member" (Mann-Whitney U / (n·m)).

    Ties count half, so a constant score gives exactly 0.5.
    """
    m = np.asarray(member_scores, np.float64).ravel()
    n = np.asarray(nonmember_scores, np.float64).ravel()
    if m.size == 0 or n.size == 0:
        raise ValueError("both member and non-member scores required")
    # P(member_score < nonmember_score) + 0.5 P(equal)
    less = (m[:, None] < n[None, :]).sum()
    ties = (m[:, None] == n[None, :]).sum()
    return float((less + 0.5 * ties) / (m.size * n.size))
