"""Continuous-batching decode: slot-based serving with prefill/decode split.

The reference never serves its LMs at all (training loss is its only LM
output); ``models/generate.py`` added fixed-batch decoding.  This module
adds the remaining standard serving piece: **continuous batching** — new
requests join a running batch the moment a slot frees up, instead of
waiting for the whole batch to finish (the static-batch regime wastes
(B-1)/B of the chip whenever lengths diverge).

TPU-first shape discipline — the classic continuous-batching schedulers
(Orca, vLLM) re-pack a dynamic batch every iteration, which would retrace
under XLA.  Here every compiled program is static:

- ``admit`` (``_programs``): a whole admission GROUP in one dispatch — a
  vmapped prefill of the (G, W) prompt block (each row right-aligned in
  the fixed ``prefill_width`` window: left pad masked out of attention,
  rotary starting at 0, exactly ``generate()``'s ragged layout), the
  ``dynamic_update_slice`` scatter of every prefilled row cache into its
  slot of the (max_batch, ctx) serving cache, and the tokens/pos/pad
  vector updates.
- ``decode`` (``_programs``): ``decode_chunk`` lockstep tokens for ALL
  slots with PER-ROW positions (the same (B, T) row-local position
  support speculative decoding uses) — each slot sits at its own depth.

The host scheduler (``ContinuousBatcher.run``) owns all data-dependent
control flow — admissions, EOS, slot recycling — and the device only ever
sees the fixed-shape programs above.  Greedy outputs are BIT-IDENTICAL to
per-request ``generate()`` (oracle: tests/test_serving.py) because each
row's attention/rope math is independent of its neighbours.

Host-round-trip discipline (the round-4 lesson: 42 blocking fetches x
~100 ms tunnel RTT buried the batcher 5-7x under static batching on the
driver's remote chip even though the device work was smaller):

- **Group admission**: admission groups are padded to the next power of
  two (pad lanes re-write the last real admission's row — idempotent) so
  at most log2(max_batch)+1 shapes ever compile.
- **Budget mode pipelining** (``eos_id is None``): with no EOS the whole
  admit/decode/recycle schedule is a pure function of the budgets, known
  on the host in advance — so the scheduler NEVER blocks on device
  results.  It streams every admit + decode dispatch back-to-back
  (XLA's async dispatch queues them), records which (array, row, count)
  slices belong to which request, and fetches everything in ONE
  ``device_get`` at the end.  Blocking round-trips per run: 1.
- **EOS mode** (``eos_id`` set): token values drive control flow, so the
  scheduler fetches once per decode chunk (plus one firsts-fetch per
  admission group) — the minimum information it needs to schedule.
- **Fused serving** (:func:`serve_fused`): even streamed dispatches cost
  ~10 ms each over a remote tunnel, so the whole workload can instead run
  as ONE program: budget mode plans the complete schedule host-side
  (numpy, microseconds) and executes it as a ``lax.scan`` over
  precomputed admission/output tables; EOS mode runs a
  ``lax.while_loop`` that admits, decodes, and retires on device.

KV residency (``kv_layout="paged"``): the contiguous serving cache pins
``max_batch * ctx_size`` KV slots whether or not anything lives in them;
the paged layout (models/kv_pool.py + the block-table read/write path in
models/llama.py) carves one physical pool of ``kv_page``-token pages,
bit-identical in output, whose residency tracks live tokens — and whose
shared-prefix pages are refcounted across requests (prefix-cache-aware
admission).  ``serve_fused`` stays contiguous BY DESIGN: its cache is
built in-trace, lives for exactly one dispatch, and is sized by the
workload it was compiled for — there is no long-lived pool for paging to
shrink.

Composes with the rest of the serving stack: LoRA fine-tune -> merge ->
serve (merged trees are plain params), int8 (quantized trees load the same
way), and the sequence-sharded cache for long contexts.
"""

from __future__ import annotations

import dataclasses
import functools
import queue
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..data.prefetch import PrefetchStream
from . import kv_pool, lora
from .llama import Llama, LlamaConfig


class AdmissionRejected(RuntimeError):
    """Admission backpressure: the request cannot be accepted right now.
    ``reason`` names the binding constraint (``"queue_full"``,
    ``"slo"``, or ``"kv_pool"``) and ``retry_after_s`` is the
    scheduler's estimate of when it clears — clients back off
    (``resilience.retry.retry_call`` with
    ``retry_on=(AdmissionRejected,)``) instead of piling on."""

    def __init__(self, message: str, retry_after_s: float,
                 reason: str = "queue_full"):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.reason = reason


class ServedTokens(list):
    """A served request's token list plus its resilience ``status``:
    ``"ok"``, ``"timed_out"`` (deadline eviction — the tokens are the
    PARTIAL stream emitted before the deadline) or ``"poisoned"``
    (non-finite logits; tokens truncated before the first bad chunk).
    Compares equal to a plain list of the same tokens, so oracle tests
    against ``generate()`` need no unwrapping."""

    __slots__ = ("status",)

    def __init__(self, tokens=(), status: str = "ok"):
        super().__init__(tokens)
        self.status = status


@dataclass
class _Slot:
    # run() keys requests by position (int); the streaming interface by
    # user-provided hashable rid — None is the only "free" sentinel
    request_id: object = None
    # EOS mode: host ints, appended as chunks are fetched.  Budget mode:
    # (device_array, index, count) refs, resolved in ONE fetch at the end.
    emitted: list = field(default_factory=list)
    budget: int = 0
    total: int = 0
    done_eos: bool = False
    # resilience: absolute perf_counter deadline (None = unbounded) and
    # deferred poison-guard chunk flags ((ok_array, row) refs, budget
    # mode) — resolved with the tokens at end of run
    deadline: float | None = None
    ok_refs: list = field(default_factory=list)

    @property
    def free(self) -> bool:
        return self.request_id is None


@dataclass
class _ParkedStream:
    """Host-side remainder of one SPILLED stream (the tiered pool,
    ``spill="host"``): everything a fresh lane needs to resume decoding.
    ``host_pages`` is the ``jax.device_get`` copy of the stream's written
    pool pages — a VERBATIM byte copy of the pool rows (int8 values and
    their scale planes included), which is what makes the spill→prefetch
    round trip bit-exact.  ``tok``/``pos``/``pad`` are device scalars
    sliced from the lane vectors at park time (never fetched; restored
    with ``.at[slot].set``), so parking adds exactly one blocking copy:
    the page bytes."""

    rid: object
    emitted: list
    budget: int
    total: int
    ok_refs: list
    deadline: float | None
    n_pages: int        # private pages to re-allocate at resume
    n_written: int      # leading pages whose bytes ride the host tier
    host_pages: object  # device_get pool-leaf tree, (n_written, pg, ...)
    tok: object
    pos: object
    pad: object
    enq_step: int | None = None  # scheduler step the upload was enqueued
    dead: bool = False           # evicted while parked (staged copy dropped)


class _UploadFeed:
    """Work-queue adapter between the scheduler and ``PrefetchStream``'s
    producer thread: the producer blocks here until the scheduler enqueues
    a parked stream, then performs the host→device transfer
    (``jnp.asarray`` over the saved page bytes) OFF the scheduler thread —
    that transfer overlapping the current decode chunk is the whole point
    of routing resumes through data/prefetch.py."""

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._closed = False

    def put(self, handle) -> None:
        self._q.put(handle)

    def close(self) -> None:
        self._closed = True

    def next_batch(self):
        while True:
            try:
                h = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._closed:
                    raise RuntimeError("spill tier closed")
                continue
            return h, jax.tree.map(jnp.asarray, h.host_pages)


class _SpillTier:
    """The staging pipeline of the tiered KV pool — park/resume POLICY
    lives on the batcher; this owns only the double-buffered host→device
    upload path (``PrefetchStream`` over an :class:`_UploadFeed`, depth =
    ``spill_prefetch``).  ``depth=0`` disables lookahead entirely: every
    resume stages synchronously and counts as ``late``."""

    def __init__(self, depth: int):
        self.depth = max(0, int(depth))
        self._feed = _UploadFeed()
        self._stream = (PrefetchStream(self._feed, depth=self.depth)
                        if self.depth else None)

    def enqueue(self, handle: _ParkedStream, step: int) -> None:
        """Initiate staging for ``handle`` at scheduler step ``step`` —
        the hit/late accounting is by INITIATION LEAD (enqueued on an
        earlier step than the resume consuming it = hit), not wall-clock
        timing, so the counters are deterministic."""
        if self._stream is None:
            return
        handle.enq_step = step
        self._feed.put(handle)

    def collect(self, handle: _ParkedStream):
        """The staged device page tree for ``handle``.  Consumption is
        FIFO in enqueue order (resume order IS park order); entries whose
        stream was evicted while parked (``dead``) are drained and
        dropped.  Falls back to a synchronous upload when the handle was
        never enqueued (depth 0, or resume outran the lookahead)."""
        if self._stream is None or handle.enq_step is None:
            return jax.tree.map(jnp.asarray, handle.host_pages)
        while True:
            got, tree = self._stream.next_batch()
            if got is handle:
                return tree
            assert got.dead, "spill prefetch consumed out of order"

    def close(self) -> None:
        self._feed.close()
        if self._stream is not None:
            self._stream.close()


def _right_aligned_prefill(model, W: int, P: int, params, prompt_row,
                           length, prefix_cache, adapter=None):
    """prompt_row (W,) right-padded; -> (cache_row_tree, first, pad).

    The row is right-ALIGNED into the window (shift by W - length) so the
    last prompt token sits at slot W-1 and decode continues at W for every
    request regardless of its length.  With a shared prefix the window
    sits at cache slots [P, P+W) on top of the prefix row cache
    (generate.precompute_prefix), and the returned row cache carries BOTH
    — inserting it into the serving cache needs no special prefix
    handling.  Shared by every serving path (host batcher, fused
    while_loop, scheduled scan) so their prefill math cannot drift."""
    shift = W - length
    aligned = jnp.roll(prompt_row, shift)[None, :]  # (1, W)
    pad = shift[None]
    variables = params if P == 0 else {**params, "cache": prefix_cache}
    # ``adapter`` (scalar per row under vmap) threads the multi-LoRA slot
    # into the prefill so the prompt runs under the SAME adapter as the
    # decode steps that follow — kwarg omitted entirely on the base path
    # so non-LoRA programs stay literally the programs they were
    kw = {} if adapter is None else {"adapter_slots": adapter[None]}
    logits, state = model.apply(
        variables, aligned, positions=P + jnp.arange(W),
        pad=pad, prefix_len=P, mutable=["cache"], **kw,
    )
    # the last real token sits at slot W-1 (right-aligned), so its
    # logits row IS the next-token distribution
    first = jnp.argmax(logits[0, -1], axis=-1).astype(prompt_row.dtype)
    return state["cache"], first, pad[0]


def _empty_cache_of(model, max_batch: int, params):
    """Zeros of the (max_batch, ctx) serving-cache tree.

    Callable from inside OR outside a jit trace: a one-token apply yields
    the cache shapes, and since only shapes are used, XLA dead-code-
    eliminates the forward itself.  NEVER call this per-request outside
    jit — the flax trace costs ~0.7 s of host time at d=288 (round 5:
    it tripled serve_fused's wall time as a per-call ``eval_shape``)."""
    tok = jnp.zeros((max_batch, 1), jnp.int32)
    vars_ = jax.eval_shape(
        lambda p: model.apply(
            p, tok, positions=jnp.zeros((max_batch, 1), jnp.int32),
            mutable=["cache"],
        )[1],
        params,
    )
    return jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype),
                        vars_["cache"])


def _make_empty_cache(model, max_batch: int):
    """Jitted empty-cache builder: the flax shape trace happens once per
    (model, max_batch, params-shape) at compile; later calls are ~free."""
    return jax.jit(functools.partial(_empty_cache_of, model, max_batch))


def _make_empty_pool(model, kv_page: int):
    """Jitted PAGED-pool builder: same cache tree as :func:`_empty_cache_of`
    but with every (B, ctx, ...) leaf re-carved into (nr_pages, kv_page,
    ...) physical pages (models/kv_pool.py; page 0 is the reserved null
    page).  ``nr_pages`` is static — the pool is sized once at batcher
    construction, not per max_batch*ctx worst case (that being the whole
    point)."""

    @functools.partial(jax.jit, static_argnames=("nr_pages",))
    def build(params, nr_pages: int):
        tmpl = _empty_cache_of(model, 1, params)
        return jax.tree.map(
            lambda a: jnp.zeros((nr_pages, kv_page) + a.shape[2:], a.dtype),
            tmpl,
        )

    return build


def _decode_step(model: "nn.Module", P: int, params, pad, carry, _=None, *,
                 check=False, tables=None, adapters=None):
    """One lockstep greedy decode step for all slots at their own depths —
    the scan body every serving path shares (host batcher chunks, fused
    while_loop, scheduled scan), so the bit-identical-to-generate()
    contract rests on exactly one copy of the math.

    ``check`` (keyword-only: the fused call sites pass positionally and
    stay on the plain path) additionally emits a per-row all-finite flag
    over the step's logits — the batcher's poison guard.  The token math
    is untouched either way.

    ``tables`` (keyword-only, (B, ctx // kv_page) int32) switches the
    carry's cache to the PAGED pool layout (models/kv_pool.py): the model
    routes every cache read/write through the block table; the logical
    values the attention math sees are identical, so paged streams stay
    bit-equal to contiguous ones.

    Under ``decode_impl='fused'`` (paged only) the step's tail — argmax,
    the per-leaf KV append the forward deferred, the position advance —
    collapses into ONE Pallas program (ops/fused_decode_step.py); the
    kernel replicates ``jnp.argmax``'s tie/NaN order and the unfused
    scatter bit for bit, so fused streams stay on the same bit-identity
    contract (tests/test_serving_fused_step.py)."""
    cache, tok, pos = carry
    fused = tables is not None and model.config.decode_impl == "fused"
    if fused and adapters is not None:
        raise NotImplementedError(
            "multi-LoRA decode is restricted to decode_impl='xla' (the "
            "batcher forces it); the fused Pallas step has no adapter "
            "gather")
    if fused:
        from ..ops.fused_decode_step import fused_decode_step

        logits, state = model.apply(
            {**params, "cache": cache}, tok[:, None],
            positions=pos[:, None], pad=pad, prefix_len=P,
            block_tables=tables, mutable=["cache", "pending"],
        )
        nxt, cache, pos = fused_decode_step(
            logits[:, 0], state["cache"], state["pending"], tables, pos
        )
        nxt = nxt.astype(tok.dtype)
        if check:
            ok = jnp.isfinite(logits[:, 0]).all(axis=-1)
            return (cache, nxt, pos), (nxt, ok)
        return (cache, nxt, pos), nxt
    kw = {} if adapters is None else {"adapter_slots": adapters}
    logits, state = model.apply(
        {**params, "cache": cache}, tok[:, None],
        positions=pos[:, None], pad=pad, prefix_len=P,
        block_tables=tables, mutable=["cache"], **kw,
    )
    nxt = jnp.argmax(logits[:, 0], axis=-1).astype(tok.dtype)
    if check:
        ok = jnp.isfinite(logits[:, 0]).all(axis=-1)
        return (state["cache"], nxt, pos + 1), (nxt, ok)
    return (state["cache"], nxt, pos + 1), nxt


def _validate_workload(requests, budgets, *, prefill_width: int,
                       prefix_len: int, decode_chunk: int, ctx_size: int):
    """Shared input validation for ContinuousBatcher.run and serve_fused
    (one copy: the ctx-overrun formula and the prompt checks must not
    drift between the streaming and fused entry points)."""
    if len(budgets) != len(requests):
        raise ValueError(
            f"{len(budgets)} budgets for {len(requests)} requests"
        )
    if any(b < 0 for b in budgets):
        raise ValueError(
            f"negative budget in {budgets}: a request cannot owe "
            "tokens (and the scheduler would wait on it forever)"
        )
    # chunked decode can overrun a finished row's budget by up to chunk-1
    # scratch steps before the slot is recycled; those writes must stay
    # inside the cache.  No decode runs at all when every budget is zero.
    worst = max(budgets, default=0)
    overrun = (decode_chunk - 1) if worst > 0 else 0
    if prefix_len + prefill_width + worst + overrun > ctx_size:
        raise ValueError(
            f"prefix + prefill_width + max_new_tokens + "
            f"(decode_chunk - 1) ({prefix_len}+{prefill_width}"
            f"+{worst}+{overrun}) exceeds ctx_size ({ctx_size})"
        )
    for i, r in enumerate(requests):
        if len(r) < 1:
            raise ValueError(
                f"request {i}: empty prompt (generate()'s contract "
                "requires length >= 1; an all-pad attention row would "
                "softmax over nothing and emit NaN-argmax garbage)"
            )
        if len(r) > prefill_width:
            raise ValueError(
                f"request {i}: prompt length {len(r)} exceeds "
                f"prefill_width {prefill_width}"
            )


def _paged_programs(model, W: int, P: int, kv_page: int):
    """The paged-layout admit/decode pair (cached under :func:`_programs`'
    lru with ``kv_page`` in the key).

    Prefill itself stays CONTIGUOUS — the vmapped right-aligned window
    math is untouched, so its outputs cannot drift from the contiguous
    path's.  What changes is where the row caches land: ``admit`` copies
    each prefilled row's logical pages ``[P // kv_page, ceil((P + W) /
    kv_page))`` into the slot's freshly allocated physical pages (a static
    G x n_copy unrolled ``dynamic_update_slice`` loop over the
    ``copy_dst`` table the host allocator filled).  The boundary page of a
    non-page-aligned prefix is exact because the row cache was built ON
    the prefix cache and carries the prefix KV below the window.
    ``decode`` is the same chunk scan with the block tables threaded to
    the model."""

    @jax.jit
    def admit(params, pool, rows, lengths, slots, tokens, pos, pad,
              copy_dst, prefix_cache=None, adapters=None):
        """copy_dst (G, n_copy) int32: physical destination page for each
        admitted row's c-th copied logical page.  Pad lanes repeat the
        last real admission (same pages, same data — idempotent), exactly
        like the contiguous scatter.  ``adapters`` (G,) int32 — the
        multi-LoRA slot each admitted row prefills under (pad lanes
        repeat the last real slot, idempotent like the rows)."""
        if adapters is None:
            row_caches, firsts, pads = jax.vmap(
                functools.partial(_right_aligned_prefill, model, W, P),
                in_axes=(None, 0, 0, None),
            )(params, rows, lengths, prefix_cache)
        else:
            row_caches, firsts, pads = jax.vmap(
                functools.partial(_right_aligned_prefill, model, W, P),
                in_axes=(None, 0, 0, None, 0),
            )(params, rows, lengths, prefix_cache, adapters)
        lo = P // kv_page
        for g in range(rows.shape[0]):
            for c in range(copy_dst.shape[1]):
                start = (lo + c) * kv_page
                pool = jax.tree.map(
                    lambda big, rc: jax.lax.dynamic_update_slice(
                        big,
                        rc[g][:, start:start + kv_page].astype(big.dtype),
                        (copy_dst[g, c],) + (0,) * (big.ndim - 1),
                    ),
                    pool, row_caches,
                )
        tokens = tokens.at[slots].set(firsts)
        pos = pos.at[slots].set(P + W)
        pad = pad.at[slots].set(pads)
        return pool, tokens, pos, pad, firsts

    @functools.partial(jax.jit, static_argnames=("nr", "check"))
    def decode(params, pool, tokens, pos, pad, tables, adapters=None,
               nr=1, check=False):
        """Contiguous ``decode`` with the block tables riding along — the
        scan body is the same single copy of the math (_decode_step), so
        the bit-identity contract is structural, not empirical.
        ``adapters`` (B,) int32 rides along like the tables: the per-slot
        multi-LoRA gather index (slot 0 = null adapter = base math)."""
        (pool, last, final_pos), ys = jax.lax.scan(
            functools.partial(_decode_step, model, P, params, pad,
                              check=check, tables=tables,
                              adapters=adapters),
            (pool, tokens, pos), None, length=nr,
        )
        if check:
            toks, ok = ys
            return pool, toks.T, final_pos, last, ok.all(axis=0)
        return pool, ys.T, final_pos, last

    return admit, decode, _make_empty_pool(model, kv_page)


@functools.lru_cache(maxsize=8)
def _programs(config: LlamaConfig, max_batch: int, prefill_width: int,
              prefix_len: int = 0, kv_page: int = 0):
    # eos handling is entirely host-side (the scheduler), so it is NOT part
    # of the compiled programs or their cache key
    cfg = dataclasses.replace(config, decode=True)
    model = Llama(cfg)
    W = prefill_width
    P = prefix_len
    if kv_page:
        return _paged_programs(model, W, P, kv_page)

    @jax.jit
    def admit(params, cache, rows, lengths, slots, tokens, pos, pad,
              prefix_cache=None):
        """ONE dispatch admits a whole group: vmapped prefill of the
        (G, W) prompt block, scatter of each prefilled row cache into its
        slot, and the tokens/pos/pad vector updates.  G is a trace-time
        shape (the scheduler pads groups to powers of two, repeating the
        last real admission — re-writing identical data is idempotent),
        so at most log2(max_batch)+1 variants compile."""
        row_caches, firsts, pads = jax.vmap(
            functools.partial(_right_aligned_prefill, model, W, P),
            in_axes=(None, 0, 0, None),
        )(params, rows, lengths, prefix_cache)
        for g in range(rows.shape[0]):
            cache = jax.tree.map(
                lambda big, rc: jax.lax.dynamic_update_slice(
                    big, rc[g].astype(big.dtype),
                    (slots[g],) + (0,) * (big.ndim - 1),
                ),
                cache, row_caches,
            )
        tokens = tokens.at[slots].set(firsts)
        pos = pos.at[slots].set(P + W)
        pad = pad.at[slots].set(pads)
        return cache, tokens, pos, pad, firsts

    @functools.partial(jax.jit, static_argnames=("nr", "check"))
    def decode(params, cache, tokens, pos, pad, nr=1, check=False):
        """``nr`` lockstep tokens for every slot at its own depth.

        tokens (B,), pos (B,) the slot each row writes first, pad (B,)
        left-pad widths.  Returns (new_cache, emitted (B, nr), pos + nr)
        — a ``lax.scan`` of single-token steps, so one DISPATCH yields
        ``nr`` tokens (the scheduler intervenes only at chunk boundaries;
        over a remote tunnel per-dispatch RTT would otherwise dominate).
        Each step feeds its argmax forward exactly like generate()'s
        scan, so per-row streams are bit-identical at any chunking.

        ``check`` (the batcher's poison guard) appends a (B,) bool —
        every step of this chunk produced all-finite logits for the row —
        as a fifth output; the token math is identical, so guarded and
        unguarded streams stay bit-equal."""
        (cache, last, final_pos), ys = jax.lax.scan(
            functools.partial(_decode_step, model, P, params, pad,
                              check=check),
            (cache, tokens, pos), None, length=nr,
        )
        # ``last`` == toks[:, -1]; returning it saves the scheduler a
        # separate slice dispatch per chunk (each dispatch costs ~10 ms
        # over the remote tunnel, measured round 5)
        if check:
            toks, ok = ys
            return cache, toks.T, final_pos, last, ok.all(axis=0)
        return cache, ys.T, final_pos, last  # toks (B, nr)

    return admit, decode, _make_empty_cache(model, max_batch)


class ContinuousBatcher:
    """Slot-based continuous batching over a fixed ``max_batch``.

    ``prefill_width`` is the static prompt window: prompts longer than it
    are rejected (pick the serving bucket for your traffic); shorter ones
    are left-padded for free.  ``config.ctx_size`` must cover
    ``prefix_len + prefill_width + max_new_tokens + (decode_chunk - 1)``
    (prefix_len = 0 without a shared prefix) — the chunk tail are scratch
    writes a recycled slot overwrites, but they must land inside the
    cache.

    ``kv_layout="paged"`` swaps the (max_batch, ctx) serving cache for a
    pool of ``kv_page``-token physical pages with per-slot block tables
    (models/kv_pool.py; docs/PERFORMANCE.md §7): outputs stay
    BIT-IDENTICAL for every trajectory (tests/test_serving_paged.py pins
    the full fault matrix), but resident KV bytes track LIVE tokens —
    pages return to the pool the moment a slot completes, times out, or
    is scrubbed — so a pool sized for expected concurrency (``kv_pages``)
    runs the same traffic in a fraction of the contiguous footprint.
    Requests sharing ``prefix_tokens`` map their block-table heads onto
    one refcounted copy of the prefix pages and skip its prefill work
    entirely.
    """

    def __init__(self, config: LlamaConfig, params, *, max_batch: int = 8,
                 prefill_width: int = 64, eos_id: int | None = None,
                 decode_chunk: int = 1, prefix: tuple | None = None,
                 max_queue: int | None = None, poison_guard: bool = False,
                 fault_plan=None, kv_layout: str = "contiguous",
                 kv_page: int = 16, kv_pages: int | None = None,
                 prefix_tokens=None, slo_deadline_s: float | None = None,
                 kv_dtype: str = "f32", spill: str = "off",
                 spill_after: int = 2, spill_prefetch: int = 2,
                 adapter_slots: int = 0, adapter_store: dict | None = None,
                 adapter_resident: dict | None = None):
        # ``params`` is the full variables dict ({"params": ...}), the same
        # contract as models.generate.generate / speculative_generate.
        # ``decode_chunk``: tokens per decode dispatch — admissions happen
        # at chunk boundaries, so larger chunks trade slot-refill latency
        # for nr-fold less dispatch overhead (vital over a remote tunnel).
        #
        # Resilience (docs/RESILIENCE.md):
        # ``max_queue``     bounded streaming queue — ``submit`` raises
        #                   AdmissionRejected(retry_after_s) when full;
        # ``poison_guard``  screen decode logits for non-finite values and
        #                   evict (+ quarantine) poisoned slots;
        # ``fault_plan``    resilience.FaultPlan — its ``serve_timeout``
        #                   rate injects deterministic request stalls
        #                   (evicted as ``timed_out``).
        #
        # Paged KV (docs/PERFORMANCE.md §7):
        # ``kv_layout``     "contiguous" (default; one (max_batch, ctx) KV
        #                   row per slot) or "paged" — the cache becomes a
        #                   pool of ``kv_page``-token physical pages and
        #                   per-slot block tables (models/kv_pool.py);
        #                   bit-identical outputs, resident KV tracks live
        #                   tokens instead of the worst case;
        # ``kv_pages``      pool size (default: enough that allocation can
        #                   never fail — sizing it SMALLER is the memory
        #                   win; admission then queues on the pool);
        # ``prefix_tokens`` shared system-prompt token ids — the batcher
        #                   precomputes the prefix itself, every prompt
        #                   must start with it (stripped on submit; the
        #                   skipped prefill work is counted as
        #                   serving_prefix_hits_total) and paged slots map
        #                   their block-table heads onto ONE shared
        #                   refcounted copy of its whole pages;
        # ``slo_deadline_s`` admission SLO: reject (with a drain-rate
        #                   derived ``retry_after_s``) requests whose
        #                   estimated queue + pool wait already exceeds it.
        #
        # Tiered / quantized pool (docs/PERFORMANCE.md §12):
        # ``kv_dtype``      pool storage dtype — "f32" (native: the pool
        #                   stores the compute dtype, bit-identical to the
        #                   pre-knob batcher), "bf16", or "int8" (pages
        #                   quantize per-(token, head), scale planes ride
        #                   the pool tree, kernels dequantize in-VMEM);
        # ``spill``         "off" or "host" — park cold streams' written
        #                   pages in host RAM when admission is blocked on
        #                   the pool, prefetch them back (double-buffered,
        #                   data/prefetch.py) when a lane + pages free up;
        # ``spill_after``   decode chunks a stream must have run before it
        #                   is park-eligible (the cold-age threshold);
        # ``spill_prefetch`` host→device staging lookahead depth (0 = no
        #                   lookahead: every resume stages synchronously
        #                   and counts as ``late``).
        #
        # Multi-tenant adapters (docs/PERFORMANCE.md multi-tenant section):
        # ``adapter_slots``   > 0 turns on batched multi-LoRA decode: the
        #                   params carry MultiLoRADense stacks of this many
        #                   slots (slot 0 = reserved null adapter, bitwise
        #                   the base model) and every submit() may name an
        #                   ``adapter_id``; residency is managed by
        #                   models/adapter_pool.AdapterPool with KV-page
        #                   discipline (refcount/LRU-evict/miss-refetch);
        # ``adapter_store``   host store ``tenant -> (adapter, scale,
        #                   round_ix)`` — the miss re-fetch source, shared
        #                   across a fleet's replicas by the tenants plane;
        # ``adapter_resident`` ``tenant -> slot`` already INSTALLED in the
        #                   passed-in (pre-stacked) params — seeded as
        #                   resident without a device write (how rollout
        #                   replicas built from pushed params come up hot).
        if config.decode_seq_shards > 1:
            raise NotImplementedError(
                "continuous batching over the sequence-sharded cache: use "
                "one batcher per replica today"
            )
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'contiguous' or 'paged', got "
                f"{kv_layout!r}"
            )
        if kv_dtype not in kv_pool.KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {sorted(kv_pool.KV_DTYPES)}, "
                f"got {kv_dtype!r}"
            )
        if kv_dtype != "f32" and kv_layout != "paged":
            raise ValueError(
                f"kv_dtype={kv_dtype!r} is a paged-pool layout knob "
                "(kv_layout='paged'); the contiguous cache stores the "
                "compute dtype"
            )
        self.kv_dtype = kv_dtype
        if kv_dtype == "int8":
            # reuse the existing int8 cache path wholesale (models/
            # llama.py ``quant``, ops/flash_decode.py ``_kernel_int8``):
            # pool leaves become int8 pages plus f32 per-(token-in-page,
            # head) scale planes, upcast INSIDE the consuming kernels —
            # the f32 copy of the pool never exists.  Replaced before
            # ``with_resolved_decode_impl`` / prefix precompute so the
            # compiled programs and the prefix cache share the layout.
            config = dataclasses.replace(config, kv_cache_int8=True)
        elif kv_dtype == "bf16":
            config = dataclasses.replace(config, kv_cache_dtype="bfloat16")
        if spill not in ("off", "host"):
            raise ValueError(f"spill must be 'off' or 'host', got {spill!r}")
        if spill != "off" and kv_layout != "paged":
            raise ValueError("spill='host' requires kv_layout='paged' "
                             "(the contiguous cache has no pool to tier)")
        if spill_after < 1:
            raise ValueError(
                f"spill_after must be >= 1 (a stream must decode at least "
                f"one chunk before it can be cold), got {spill_after}"
            )
        if spill_prefetch < 0:
            raise ValueError(
                f"spill_prefetch must be >= 0, got {spill_prefetch}"
            )
        self.adapter_slots = int(adapter_slots)
        if self.adapter_slots:
            if self.adapter_slots < 2:
                raise ValueError(
                    f"adapter_slots={adapter_slots}: need slot 0 (the "
                    "reserved null adapter) plus at least one tenant slot")
            if kv_layout != "paged":
                raise ValueError(
                    "adapter_slots requires kv_layout='paged' — the "
                    "adapter pool shares the paged pool's residency "
                    "model (and its HBM budget)")
            if config.lora_rank <= 0:
                raise ValueError(
                    "adapter_slots needs config.lora_rank > 0 (the "
                    "factor stacks are sized by the rank)")
            if prefix is not None or prefix_tokens is not None:
                raise ValueError(
                    "adapter_slots does not compose with a shared prefix "
                    "cache: the prefix KV is computed under the BASE "
                    "model, so a tenant's decode over it would diverge "
                    "from the merge_lora parity contract")
            if spill != "off":
                raise NotImplementedError(
                    "adapter_slots with spill='host': parked streams "
                    "would hold adapter refcounts across park/resume — "
                    "not wired yet")
            # multi-LoRA decode is an XLA-path feature: the fused Pallas
            # step has no per-slot adapter gather.  Replaced BEFORE
            # with_resolved_decode_impl so 'auto' cannot pick fused, and
            # before _programs sees the config (lora_slots is part of its
            # lru key, so adapter programs never collide with base ones).
            config = dataclasses.replace(
                config, lora_slots=self.adapter_slots, decode_impl="xla")
            params = lora.stack_adapter_params(params, config)
        elif adapter_store is not None or adapter_resident:
            raise ValueError(
                "adapter_store/adapter_resident need adapter_slots > 0")
        self._spill_on = spill == "host"
        self.spill_after = int(spill_after)
        self.config = config
        self.params = params
        self.max_batch = max_batch
        self.prefill_width = prefill_width
        self.eos_id = -1 if eos_id is None else int(eos_id)
        if decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, got {decode_chunk}")
        self.decode_chunk = decode_chunk
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        if slo_deadline_s is not None and slo_deadline_s <= 0:
            raise ValueError(
                f"slo_deadline_s={slo_deadline_s} must be > 0"
            )
        self.slo_deadline_s = slo_deadline_s
        # shared-prefix serving (system prompt / few-shot header): the
        # result of generate.precompute_prefix; every admission prefills
        # on top of it and every slot decodes past it.  ``prefix_tokens``
        # is the self-service form: the batcher precomputes the prefix and
        # owns the prompt-stripping contract (prefix-cache-aware
        # admission).
        if prefix_tokens is not None:
            if prefix is not None:
                raise ValueError(
                    "pass prefix= (a precomputed cache) or prefix_tokens= "
                    "(token ids the batcher precomputes), not both"
                )
            from .generate import precompute_prefix
            self._prefix_tokens = tuple(int(t) for t in prefix_tokens)
            prefix = precompute_prefix(
                config, params,
                jnp.asarray(self._prefix_tokens, jnp.int32),
            )
        else:
            self._prefix_tokens = None
        self._prefix_cache, self.prefix_len = (
            prefix if prefix is not None else (None, 0)
        )
        # pin 'auto' decode_impl from the params' device before the config
        # becomes _programs' lru_cache key
        config = self.config = config.with_resolved_decode_impl(params)
        self.kv_page = int(kv_page) if self._paged else 0
        if self._paged:
            if self.kv_page < 1:
                raise ValueError(f"kv_page must be >= 1, got {kv_page}")
            if config.ctx_size % self.kv_page:
                raise ValueError(
                    f"ctx_size {config.ctx_size} must be a multiple of "
                    f"kv_page {self.kv_page}"
                )
        self._admit_fn, self._decode, empty = _programs(
            config, max_batch, prefill_width, self.prefix_len, self.kv_page
        )
        if self._paged:
            pg = self.kv_page
            P = self.prefix_len
            self._n_slot_pages = config.ctx_size // pg
            self._head_len = P // pg  # WHOLE pages of shared prefix
            # logical pages the admit program copies from the prefill row
            # cache: [P // pg, ceil((P + W) / pg)) — the boundary page of
            # an unaligned prefix rides along (private, exact: the row
            # cache carries the prefix KV below the window)
            self._n_copy = -(-(P + prefill_width) // pg) - self._head_len
            if kv_pages is None:
                # never-fails sizing: the head pages once, plus every
                # slot's worst-case private pages, plus the null page.
                # Sizing SMALLER is the point of paging — admission then
                # waits on the pool (head-of-line, deterministic).
                kv_pages = 1 + self._head_len + max_batch * (
                    self._n_slot_pages - self._head_len
                )
                if self.adapter_slots:
                    # shared HBM budget: the adapter stacks live next to
                    # the KV pool, so the default pool shrinks by the
                    # pages they displace (floored at one slot's worst
                    # case so the batcher can always make progress) —
                    # adapter_bytes is the analytic the mem_estimate tool
                    # cross-checks against compiled argument bytes
                    from .adapter_pool import adapter_bytes
                    page_bytes = kv_pool.kv_bytes(
                        pg, config.nr_layers, config.kv_heads,
                        config.head_dim, dtype=kv_dtype)
                    shrink = kv_pool.pages_displaced(
                        adapter_bytes(config), page_bytes)
                    floor = 1 + self._head_len + self._n_slot_pages
                    kv_pages = max(floor, kv_pages - shrink)
            self._pool = kv_pool.KVPagePool(int(kv_pages))
            self._registry = kv_pool.PrefixRegistry(self._pool)
            self._tables = np.zeros(
                (max_batch, self._n_slot_pages), np.int32
            )
            self._head_pages: list = []
            if self._head_len:
                head = self._pool.alloc(self._head_len)
                if head is None:
                    raise ValueError(
                        f"kv_pages={kv_pages} cannot hold the "
                        f"{self._head_len} shared prefix pages"
                    )
                self._head_pages = head
            self.cache = empty(params, nr_pages=self._pool.nr_pages)
            if self._head_pages:
                # install the precomputed prefix KV into its shared
                # read-only pages (once; every admission just points its
                # table head here)
                ix = jnp.asarray(self._head_pages, jnp.int32)
                n_tok = self._head_len * pg
                self.cache = jax.tree.map(
                    lambda pool_a, pc: pool_a.at[ix].set(
                        pc[0, :n_tok].reshape(
                            (self._head_len, pg) + pc.shape[2:]
                        ).astype(pool_a.dtype)
                    ),
                    self.cache, self._prefix_cache,
                )
                if self._prefix_tokens is not None:
                    # the registry takes over the base reference; each
                    # admitted slot adds (and later drops) one more
                    self._registry.put(self._prefix_tokens,
                                       self._head_pages)
        else:
            self._pool = None
            self._registry = None
            self._tables = None
            self._head_pages = []
            self._head_len = 0
            self.cache = empty(params)
        self.pos = jnp.zeros((max_batch,), jnp.int32)
        self.pad = jnp.zeros((max_batch,), jnp.int32)
        self.tokens = jnp.zeros((max_batch,), jnp.int32)
        self.slots = [_Slot() for _ in range(max_batch)]
        # multi-tenant adapter state: the pool decides WHICH stack slot a
        # tenant occupies; ``_adapter_vec`` (host numpy, shipped as an
        # owned copy per dispatch exactly like the block tables) is the
        # per-LANE gather index the decode step reads; ``_slot_tenant``
        # maps lanes back to tenants for idempotent refcount release.
        if self.adapter_slots:
            from .adapter_pool import AdapterPool
            self._adapters = AdapterPool(self.adapter_slots,
                                         store=adapter_store)
            if adapter_resident:
                for t, ps in sorted(adapter_resident.items(),
                                    key=lambda kv: kv[1]):
                    self._adapters.seed(t, ps)
            self._adapter_vec = np.zeros((max_batch,), np.int32)
        else:
            self._adapters = None
            self._adapter_vec = None
        self._slot_tenant: list = [None] * max_batch
        # resilience state
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.poison_guard = bool(poison_guard)
        self.fault_plan = fault_plan
        self._quarantined: set[int] = set()  # poisoned slots, out of rotation
        # paged quarantine: a poisoned slot's PRIVATE pages hold NaN K/V a
        # reallocated page would leak (0 * NaN through the value einsum),
        # so they are held out of the pool until scrub() zeroes them
        self._qpages: dict = {}  # slot -> held private pages
        self._hit_rids: set = set()  # queued rids that matched the prefix
        self._drain_pps = 0.0  # EWMA pages-freed/sec (SLO admission)
        self._free_t: float | None = None
        self._status: dict = {}  # rid -> non-ok status for the current run
        self._deadlines: dict = {}  # rid -> deadline_s; the clock starts
        # at ADMISSION (decode-time bound; queue wait is the backpressure
        # knob's job, not the deadline's)
        self._okrefs: dict = {}  # rid -> deferred poison-guard chunk refs
        self._chunk_s = 0.0  # EWMA of fenced chunk wall time (backpressure)
        # streaming interface state (submit/step/drain)
        self._queue: list = []
        self._instant: dict = {}  # zero-budget submissions, returned next step
        # serving telemetry: how full the batch ran, admissions, steps
        self.stats = {"decode_steps": 0, "slot_steps": 0, "active_steps": 0,
                      "admitted": 0, "prefix_hits": 0, "prefix_hit_tokens": 0}
        # obs stamps: rid -> submit/run-entry perf_counter (only written
        # while telemetry is enabled; queue-wait and request-latency
        # histograms are derived from these host-side)
        self._req_ts: dict = {}
        # tiered-pool state (``spill="host"``; docs/PERFORMANCE.md §12).
        # Parked streams in park order — resume is head-of-line FIFO over
        # this deque, with priority over fresh admissions — plus the
        # host→device staging pipeline and the per-slot cold-age counters
        # (decode chunks since admission).  All of it is inert when spill
        # is off: the deque stays empty and no code path below touches
        # device state, preserving the bit-identity contract.
        self._parked: deque = deque()
        self._tier = _SpillTier(spill_prefetch) if self._spill_on else None
        self._slot_age = [0] * max_batch
        self._sched_step = 0
        self._int8 = kv_dtype == "int8"
        # per-page quantized bytes (K + V int8 values + f32 scale planes,
        # all layers) — the serving_kv_dequant_bytes_total unit
        self._page_qbytes = (kv_pool.kv_bytes(
            self.kv_page, config.nr_layers, config.kv_heads,
            config.head_dim, dtype="int8") if self._int8 else 0)

    # -- telemetry (all no-ops while ddl25spring_tpu.obs is disabled) ----

    def _obs_admitted(self, admissions):
        """Queue-wait per admitted request: admission is when a request
        stops waiting and starts occupying a lane.  The wait histogram
        carries the request's trace id as its exemplar, so a burning
        queue-wait SLO window links straight to offending traces."""
        if not self._req_ts:
            return
        rt = obs.reqtrace()
        now = time.perf_counter()
        for _s, rid, _p, _b in admissions:
            t0 = self._req_ts.get(rid)
            if t0 is None:
                continue
            wait = now - t0
            obs.observe("serving_queue_wait_seconds", wait,
                        exemplar=(rt.trace_id_of(rid)
                                  if rt is not None else None))
            if rt is not None:
                rt.note(rid, "admit",
                        replica=getattr(self, "_replica_ix", None),
                        seconds=wait)

    def _obs_finish(self, rids):
        """Request latency at the moment tokens became host-visible."""
        if not self._req_ts:
            return
        rt = obs.reqtrace()
        now = time.perf_counter()
        for rid in rids:
            t0 = self._req_ts.pop(rid, None)
            if t0 is None:
                continue
            obs.observe("serving_request_seconds", now - t0,
                        exemplar=(rt.trace_id_of(rid)
                                  if rt is not None else None))
            if rt is not None:
                rt.note(rid, "finish",
                        replica=getattr(self, "_replica_ix", None),
                        seconds=now - t0)

    # -- paged-pool + prefix bookkeeping ---------------------------------

    def _strip_prefix(self, prompt):
        """With ctor-level ``prefix_tokens`` every prompt must carry the
        shared prefix verbatim (the compiled programs bake its static
        length in); returns the remainder that actually gets prefilled.
        Raises on a mismatch — silently serving a prompt AGAINST a prefix
        it doesn't share would answer the wrong question."""
        if self._prefix_tokens is None:
            return prompt
        p = [int(t) for t in prompt]
        n = len(self._prefix_tokens)
        if len(p) <= n or tuple(p[:n]) != self._prefix_tokens:
            raise ValueError(
                f"prompt must start with the {n} shared prefix tokens "
                "(prefix_tokens=) and continue past them"
            )
        return p[n:]

    def _pages_needed(self, budget: int, *, resident: bool = False) -> int:
        """Private pages one admission holds for its whole trajectory;
        ``resident=True`` prices the DEVICE-resident floor under the
        tiered pool instead (kv_pool.pages_needed ``spill=``) — what the
        SLO admission estimate charges queued-ahead requests when cold
        pages can spill."""
        return kv_pool.pages_needed(
            self.prefill_width, budget, self.kv_page,
            prefix_len=self.prefix_len, decode_chunk=self.decode_chunk,
            spill=resident,
        )

    def _check_pool_capacity(self, budgets, label=None):
        """Upfront rejection of requests the pool could NEVER admit (need
        exceeds total private capacity) — queueing them would deadlock the
        head-of-line admission."""
        if not self._paged:
            return
        cap = self._pool.nr_pages - 1 - self._head_len
        for i, b in enumerate(budgets):
            need = self._pages_needed(b) if b > 0 else 0
            if need > cap:
                who = label if label is not None else f"request {i}"
                raise ValueError(
                    f"{who}: needs {need} KV pages but the pool holds "
                    f"only {cap} private pages (raise kv_pages or lower "
                    "max_new_tokens)"
                )

    def _release_pages(self, s: int):
        """Return slot ``s``'s pages to the pool at recycle time
        (completion or deadline eviction): the shared prefix head drops
        one reference, private pages free outright, and the table row
        zeroes so the lane's post-recycle scratch writes land on the null
        page.  Also feeds the drain-rate EWMA the SLO admission estimates
        ride on."""
        if not self._paged:
            return
        self._release_adapter(s)
        hp = self._head_len
        private = [int(p) for p in self._tables[s, hp:] if p > 0]
        if hp and self._tables[s, 0] > 0:
            self._pool.free(self._head_pages)
        if private:
            self._pool.free(private)
            now = time.perf_counter()
            if self._free_t is not None and now > self._free_t:
                rate = len(private) / (now - self._free_t)
                self._drain_pps = (0.7 * self._drain_pps + 0.3 * rate
                                   if self._drain_pps else rate)
            self._free_t = now
        self._tables[s, :] = 0
        if obs.enabled():
            obs.set_gauge("serving_kv_pages_in_use",
                          self._pool.pages_in_use)
            obs.set_gauge("serving_kv_resident_pages",
                          self._pool.resident_pages, tier="device")

    def _release_adapter(self, s: int):
        """Drop lane ``s``'s adapter reference (idempotent — eviction
        paths and the normal recycle can both land here) and park the
        lane's further scratch decodes on the null adapter."""
        t = self._slot_tenant[s]
        if t is None:
            return
        self._slot_tenant[s] = None
        self._adapter_vec[s] = 0
        self._adapters.release(t)

    # -- tiered pool: park / prefetch / resume (spill="host") ------------

    def _obs_kv_residency(self):
        """Per-tier residency gauges: ``tier="device"`` is the pool's
        allocated pages, ``tier="host"`` the spilled page buffers."""
        if obs.enabled():
            obs.set_gauge("serving_kv_resident_pages",
                          self._pool.resident_pages, tier="device")
            obs.set_gauge("serving_kv_resident_pages",
                          self._pool.spilled_pages, tier="host")

    def _park_slot(self, s: int):
        """Spill slot ``s``'s stream to the host tier: device_get its
        WRITTEN pages (a verbatim byte copy, scale planes included — the
        one blocking copy parking costs; budget-mode pipelining pays this
        fence only when a spill actually triggers), free the lane and ALL
        its pages (head reference included), and append the parked handle.
        The freed frames are what the blocked admission gets."""
        sl = self.slots[s]
        hp = self._head_len
        pg = self.kv_page
        private = [int(p) for p in self._tables[s, hp:] if p > 0]
        # content extent is host-known without a fetch: prefill wrote
        # [0, P+W) and every chunk since advanced all lanes by K
        written = (self.prefix_len + self.prefill_width
                   + self._slot_age[s] * self.decode_chunk)
        n_written = min(len(private), max(0, -(-written // pg) - hp))
        h = _ParkedStream(
            rid=sl.request_id, emitted=sl.emitted, budget=sl.budget,
            total=sl.total, ok_refs=sl.ok_refs, deadline=sl.deadline,
            n_pages=len(private), n_written=n_written, host_pages=None,
            tok=self.tokens[s], pos=self.pos[s], pad=self.pad[s],
        )
        if n_written:
            ix = jnp.asarray(private[:n_written], jnp.int32)
            h.host_pages = jax.device_get(
                jax.tree.map(lambda big: big[ix], self.cache))
        if hp and self._tables[s, 0] > 0:
            self._pool.free(self._head_pages)
        if private:
            self._pool.free(private)
        self._tables[s, :] = 0
        self._pool.note_spill(n_written)
        self.slots[s] = _Slot()
        self._slot_age[s] = 0
        self._parked.append(h)
        obs.inc("serving_kv_spills_total", n_written)
        self._obs_kv_residency()

    def _make_room(self, need: int):
        """Park cold streams until ``need`` pages are free or nobody is
        park-eligible.  Victim order is ascending slot index over active,
        non-quarantined, unfinished slots that have decoded at least
        ``spill_after`` chunks — deterministic, so the whole trajectory
        stays a pure function of the request sequence."""
        while self._pool.free_pages < need:
            victim = None
            for s, sl in enumerate(self.slots):
                if (sl.free or s in self._quarantined or sl.done_eos
                        or sl.budget <= 0):
                    continue
                if self._slot_age[s] < self.spill_after:
                    continue
                victim = s
                break
            if victim is None:
                return
            self._park_slot(victim)

    def _prefetch_ahead(self):
        """Initiate host→device staging for the next ``spill_prefetch``
        parked streams (resume order is FIFO, so the lookahead window is
        the deque head).  Runs right after admissions so the producer
        thread's uploads overlap the decode chunk below — a resume that
        consumes an upload initiated on an EARLIER step counts as a
        prefetch ``hit``."""
        if self._tier is None or self._tier.depth == 0:
            return
        for i, h in enumerate(self._parked):
            if i >= self._tier.depth:
                break
            if h.enq_step is None and h.n_written:
                self._tier.enqueue(h, self._sched_step)

    def _resume_parked(self):
        """Re-admit parked streams — head-of-line FIFO over the parked
        deque, called BEFORE fresh admissions each step so resumed
        streams have first claim on freed pages.  The staged bytes are
        written into freshly allocated frames verbatim (same dtypes,
        scale planes included), so the logical KV view — and therefore
        every subsequent greedy token — is identical to never having
        parked."""
        if not self._parked:
            return
        free = [s for s, sl in enumerate(self.slots)
                if sl.free and s not in self._quarantined]
        hp = self._head_len
        while self._parked and free:
            h = self._parked[0]
            if self._pool.free_pages < h.n_pages:
                # head-of-line ON PURPOSE, like _admit_from: resuming a
                # smaller parked stream first would make trajectories
                # depend on pool timing
                break
            self._parked.popleft()
            s = free.pop(0)
            pages = self._pool.alloc(h.n_pages)
            if self._head_pages:
                if self._prefix_tokens is not None:
                    self._registry.acquire(self._prefix_tokens)
                else:
                    self._pool.share(self._head_pages)
                self._tables[s, :hp] = self._head_pages
            self._tables[s, hp:hp + len(pages)] = pages
            self._tables[s, hp + len(pages):] = 0
            hit = h.enq_step is not None and h.enq_step < self._sched_step
            if h.n_written:
                staged = self._tier.collect(h)
                ix = jnp.asarray(pages[:h.n_written], jnp.int32)
                self.cache = jax.tree.map(
                    lambda big, st: big.at[ix].set(st), self.cache, staged)
            self.tokens = self.tokens.at[s].set(h.tok)
            self.pos = self.pos.at[s].set(h.pos)
            self.pad = self.pad.at[s].set(h.pad)
            sl = self.slots[s]
            sl.request_id = h.rid
            sl.emitted = h.emitted
            sl.budget = h.budget
            sl.total = h.total
            sl.done_eos = False
            sl.ok_refs = h.ok_refs
            sl.deadline = h.deadline
            self._slot_age[s] = 0
            self._pool.note_unspill(h.n_written)
            obs.inc("serving_kv_prefetch_total",
                    result="hit" if hit else "late")
            self._obs_kv_residency()

    def _spillable_pages(self) -> int:
        """Device pages held by park-eligible streams — pages a spill
        pass could free WITHOUT waiting for a completion (the SLO
        admission estimate credits these against the pool deficit)."""
        hp = self._head_len
        n = 0
        for s, sl in enumerate(self.slots):
            if (sl.free or s in self._quarantined or sl.done_eos
                    or sl.budget <= 0):
                continue
            if self._slot_age[s] < self.spill_after:
                continue
            n += int((self._tables[s, hp:] > 0).sum())
        return n

    def _reject(self, reason: str, message: str, retry_after: float):
        obs.inc("serving_rejected_total")
        obs.inc("serving_reject_reason_total", reason=reason)
        raise AdmissionRejected(message, retry_after, reason)

    def _admission_wait_estimate(self, budget: int):
        """Estimated seconds until a new request could be ADMITTED, and
        which constraint binds (``"slo"`` = queue drain, ``"kv_pool"`` =
        page deficit).  Queue component: recent fenced chunk times spread
        over the backlog; pool component (paged): pages this request plus
        the queued-ahead requests need beyond what's free, over the
        measured page drain rate (EWMA fed by :meth:`_release_pages`).
        Deliberately cheap and host-only — admission control must not cost
        a device round trip."""
        est_chunk = self._chunk_s if self._chunk_s > 0 else 0.05
        wait = est_chunk * (len(self._queue) / self.max_batch)
        bound = "slo"
        if self._paged:
            # under the tiered pool the queued-ahead demand is priced at
            # each request's device-RESIDENT floor (its cold pages can
            # spill), and pages held by already-cold streams count as
            # free-able — otherwise the estimate rejects requests whose
            # pages the spill pass would hand over immediately
            ahead = sum(self._pages_needed(q[2], resident=self._spill_on)
                        for q in self._queue)
            deficit = (self._pages_needed(budget) + ahead
                       - self._pool.free_pages)
            if self._spill_on and deficit > 0:
                deficit -= self._spillable_pages()
            if deficit > 0:
                pool_wait = (deficit / self._drain_pps
                             if self._drain_pps > 0
                             else est_chunk * deficit)
                if pool_wait > wait:
                    wait, bound = pool_wait, "kv_pool"
        return wait, bound

    # -- scheduling ------------------------------------------------------

    def _admit_group(self, admissions):
        """Admit ``admissions`` — a list of (slot, rid, prompt, budget) —
        in ONE device dispatch.  Returns the (G,) first-token device array
        (lane g belongs to admissions[g]); nothing is fetched here."""
        G0 = len(admissions)
        self._obs_admitted(admissions)
        G = 1 << (G0 - 1).bit_length()  # pad group to a power of two
        W = self.prefill_width
        rows = np.zeros((G, W), np.int32)
        lengths = np.zeros((G,), np.int32)
        slot_ix = np.zeros((G,), np.int32)
        for g, (s, _rid, prompt, _b) in enumerate(admissions):
            rows[g, :len(prompt)] = prompt
            lengths[g] = len(prompt)
            slot_ix[g] = s
        # pad lanes repeat the LAST real admission: the duplicate scatter
        # re-writes the same slot with the same data (idempotent)
        rows[G0:] = rows[G0 - 1]
        lengths[G0:] = lengths[G0 - 1]
        slot_ix[G0:] = slot_ix[G0 - 1]
        if self._paged:
            hp = self._head_len
            copy_dst = np.zeros((G, self._n_copy), np.int32)
            for g, (s, rid, _prompt, budget) in enumerate(admissions):
                pages = self._pool.alloc(self._pages_needed(budget))
                if pages is None:
                    # _admit_from sized the group to the free-page count
                    raise RuntimeError("KV pool exhausted mid-group")
                if self._head_pages:
                    # map the table head onto the shared prefix pages
                    # (one reference per occupant)
                    if self._prefix_tokens is not None:
                        self._registry.acquire(self._prefix_tokens)
                    else:
                        self._pool.share(self._head_pages)
                    self._tables[s, :hp] = self._head_pages
                self._tables[s, hp:hp + len(pages)] = pages
                self._tables[s, hp + len(pages):] = 0
                copy_dst[g] = pages[:self._n_copy]
                self._hit_rids.discard(rid)
            # pad lanes re-copy the last real admission's pages (idempotent)
            copy_dst[G0:] = copy_dst[G0 - 1]
        if self.prefix_len:
            # every admission skipped prefix_len tokens of prefill work
            # (the prefix prefilled ONCE at construction)
            self.stats["prefix_hits"] += G0
            self.stats["prefix_hit_tokens"] += G0 * self.prefix_len
            obs.inc("serving_prefix_hits_total", G0)
            obs.inc("serving_prefix_hit_tokens_total",
                    G0 * self.prefix_len)
        # span times DISPATCH only (no fence): budget mode's pipelining —
        # never block on device results mid-run — is the whole design
        with obs.span("serving.admit", group=G0):
            if self._paged:
                args = (
                    self.params, self.cache, jnp.asarray(rows),
                    jnp.asarray(lengths), jnp.asarray(slot_ix),
                    self.tokens, self.pos, self.pad,
                    jnp.asarray(copy_dst), self._prefix_cache,
                )
                if self._adapters is not None:
                    # per-lane gather index for the prefill: pad lanes
                    # repeat the last real slot via slot_ix (idempotent,
                    # like the rows)
                    args = args + (
                        jnp.asarray(self._adapter_vec[slot_ix]),)
                (self.cache, self.tokens, self.pos, self.pad,
                 firsts) = self._admit_fn(*args)
                if obs.enabled():
                    obs.set_gauge("serving_kv_pages_in_use",
                                  self._pool.pages_in_use)
            else:
                (self.cache, self.tokens, self.pos, self.pad,
                 firsts) = self._admit_fn(
                    self.params, self.cache, jnp.asarray(rows),
                    jnp.asarray(lengths), jnp.asarray(slot_ix), self.tokens,
                    self.pos, self.pad, self._prefix_cache,
                )
        now = (time.perf_counter()
               if self._deadlines or self.fault_plan is not None else 0.0)
        for g, (s, rid, _prompt, budget) in enumerate(admissions):
            sl = self.slots[s]
            sl.request_id = rid
            sl.emitted = [(firsts, g, 1)]
            sl.budget = budget - 1
            sl.total = budget
            sl.done_eos = False
            sl.ok_refs = []
            self._slot_age[s] = 0
            # injected stall (fault plan): the request's deadline is
            # already behind it — evicted at the next chunk boundary
            rel = self._deadlines.get(rid)
            if (self.fault_plan is not None
                    and self.fault_plan.serving_fault(rid)):
                sl.deadline = now
            else:
                sl.deadline = None if rel is None else now + rel
        self.stats["admitted"] += G0
        return firsts

    @staticmethod
    def _resolve(emitted, fetched: dict) -> list:
        """Deferred (array, index, count) refs -> host token ints, fetching
        each distinct device array at most once across the whole run (the
        ``fetched`` cache is shared) — the one blocking round-trip of a
        budget-mode run."""
        out = []
        for arr, ix, cnt in emitted:
            buf = fetched.get(id(arr))
            if buf is None:
                buf = fetched[id(arr)] = np.asarray(arr)
            if buf.ndim == 1:  # prefill firsts (G,)
                out.append(int(buf[ix]))
            else:  # decode chunk (B, K): row ix, first cnt columns
                out.extend(int(t) for t in buf[ix, :cnt])
        return out

    def _harvest(self, finished: dict, resolve: bool):
        """Move done slots' outputs to ``finished`` and recycle the slots.
        ``resolve`` fetches refs now (EOS mode resolves eagerly as part of
        its per-chunk fetch; budget mode defers — run() resolves all
        requests in one pass at the end)."""
        done_rids = []
        for s, sl in enumerate(self.slots):
            if sl.free:
                continue
            if sl.done_eos or sl.budget <= 0:
                out = sl.emitted
                if resolve:
                    if sl.done_eos and self.eos_id >= 0:
                        # generate()'s EOS semantics: keep EOS, pad rest
                        cut = out.index(self.eos_id) + 1
                        out = out[:cut]
                    out = out + [0] * (sl.total - len(out))
                if sl.ok_refs:
                    # deferred poison-guard flags ride along until the
                    # end-of-run resolve (budget mode)
                    self._okrefs[sl.request_id] = sl.ok_refs
                finished[sl.request_id] = out
                done_rids.append(sl.request_id)
                self._deadlines.pop(sl.request_id, None)
                self._release_pages(s)
                self.slots[s] = _Slot()
        if resolve:
            # tokens are host ints right here — this IS completion.  In
            # budget mode (resolve=False) nothing has been fetched yet;
            # run() observes completion after its single end-of-run fetch.
            self._obs_finish(done_rids)

    # -- resilience: deadline eviction, poison quarantine ----------------

    def _evict_expired(self, finished: dict, now: float | None = None):
        """Evict every active slot whose deadline has passed: its PARTIAL
        stream (whatever was emitted before the deadline — host ints in
        EOS/streaming mode, refs in budget mode) becomes the result,
        status ``timed_out``.  Never raises: a deadline miss is data, not
        an error."""
        rids = []
        for s, sl in enumerate(self.slots):
            if sl.free or sl.deadline is None:
                continue
            if now is None:
                now = time.perf_counter()
            if now >= sl.deadline:
                if sl.ok_refs:
                    self._okrefs[sl.request_id] = sl.ok_refs
                finished[sl.request_id] = sl.emitted
                self._status[sl.request_id] = "timed_out"
                rids.append(sl.request_id)
                obs.inc("serving_timed_out_total")
                obs.event("serving.timed_out", rid=repr(sl.request_id),
                          emitted=len(sl.emitted))
                rt = obs.reqtrace()
                if rt is not None:
                    rt.note(sl.request_id, "timed_out",
                            replica=getattr(self, "_replica_ix", None),
                            emitted=len(sl.emitted))
                self._deadlines.pop(sl.request_id, None)
                self._release_pages(s)
                self.slots[s] = _Slot()
        if self._parked:
            # parked streams keep their deadline while spilled: eviction
            # marks the handle dead (its staged upload, if any, is
            # drained and dropped at the next collect) and releases the
            # host-tier accounting — no device pages are involved
            for h in list(self._parked):
                if h.deadline is None:
                    continue
                if now is None:
                    now = time.perf_counter()
                if now >= h.deadline:
                    if h.ok_refs:
                        self._okrefs[h.rid] = h.ok_refs
                    finished[h.rid] = h.emitted
                    self._status[h.rid] = "timed_out"
                    rids.append(h.rid)
                    obs.inc("serving_timed_out_total")
                    obs.event("serving.timed_out", rid=repr(h.rid),
                              emitted=len(h.emitted), parked=True)
                    rt = obs.reqtrace()
                    if rt is not None:
                        rt.note(h.rid, "timed_out",
                                replica=getattr(self, "_replica_ix", None),
                                emitted=len(h.emitted))
                    self._deadlines.pop(h.rid, None)
                    h.dead = True
                    self._parked.remove(h)
                    self._pool.note_unspill(h.n_written)
                    self._obs_kv_residency()
        if rids:
            self._obs_finish(rids)

    def _evict_poisoned(self, active, ok_host, finished: dict):
        """Evict slots whose LAST decode chunk produced non-finite logits
        (called BEFORE the chunk's tokens are booked, so the garbage
        argmax stream never reaches the result): partial output, status
        ``poisoned``, slot quarantined out of rotation — its cache rows
        hold NaN/Inf a later occupant would read through attention."""
        rids = []
        for s in active:
            sl = self.slots[s]
            if sl.free or bool(ok_host[s]):
                continue
            finished[sl.request_id] = sl.emitted
            self._status[sl.request_id] = "poisoned"
            rids.append(sl.request_id)
            self._quarantined.add(s)
            if self._paged:
                # shared head pages drop their reference (their content is
                # clean — the poison lands at decode positions, past them);
                # PRIVATE pages hold NaN K/V and stay out of the pool until
                # scrub() zeroes them.  The zeroed table row parks the
                # lane's further scratch writes on the null page.
                hp = self._head_len
                self._qpages[s] = [int(p) for p in self._tables[s, hp:]
                                   if p > 0]
                if hp and self._tables[s, 0] > 0:
                    self._pool.free(self._head_pages)
                self._tables[s, :] = 0
                if obs.enabled():
                    obs.set_gauge("serving_kv_pages_in_use",
                                  self._pool.pages_in_use)
            obs.inc("serving_poisoned_total")
            obs.event("serving.poisoned", rid=repr(sl.request_id), slot=s)
            rt = obs.reqtrace()
            if rt is not None:
                rt.note(sl.request_id, "poisoned",
                        replica=getattr(self, "_replica_ix", None),
                        emitted=len(sl.emitted))
            self._deadlines.pop(sl.request_id, None)
            self._release_adapter(s)
            self.slots[s] = _Slot()
        if rids:
            self._obs_finish(rids)

    def scrub(self):
        """Zero the cache state of quarantined slots and return them to
        rotation (one dispatch).  Contiguous: the slots' cache rows.
        Paged: the held PRIVATE pages — zeroed on device, then returned to
        the pool (a reallocated page's stale NaN would otherwise leak
        through the value einsum as 0 * NaN).  The scheduler calls this
        itself when admissions starve with every usable slot quarantined;
        callers can also scrub eagerly between workloads."""
        if not self._quarantined:
            return
        if self._paged:
            pages = sorted(p for ps in self._qpages.values() for p in ps)
            if pages:
                ix = jnp.asarray(pages, jnp.int32)
                self.cache = jax.tree.map(
                    lambda big: big.at[ix].set(jnp.zeros((), big.dtype)),
                    self.cache,
                )
                for ps in self._qpages.values():
                    if ps:
                        self._pool.free(ps)
            self._qpages.clear()
            if obs.enabled():
                obs.set_gauge("serving_kv_pages_in_use",
                              self._pool.pages_in_use)
        else:
            ix = jnp.asarray(sorted(self._quarantined), jnp.int32)
            self.cache = jax.tree.map(
                lambda big: big.at[ix].set(jnp.zeros((), big.dtype)),
                self.cache,
            )
        obs.inc("serving_slots_scrubbed_total", len(self._quarantined))
        self._quarantined.clear()

    def run(self, requests, max_new_tokens, *, deadline_s=None):
        """Serve ``requests`` (list of 1-D int token prompts); returns a
        list of generated-token lists, in request order.

        ``max_new_tokens`` is an int (same budget for every request) or a
        per-request list — heterogeneous budgets are continuous batching's
        home turf: a slot whose request finishes early is refilled
        immediately.  Each output has its request's budget length,
        EOS-padded like ``generate``.

        ``deadline_s`` (scalar or per-request list; None = unbounded)
        bounds each request's DECODE time from its admission: a slot past
        its deadline is evicted at the next chunk boundary and returns its
        partial stream as :class:`ServedTokens` with status
        ``timed_out``.  Deadlines force a device fence per chunk so wall
        clock means something — budget mode loses its 1-fetch pipelining
        (the documented cost of bounded latency).  With any resilience
        feature active (deadlines, ``poison_guard``, a ``fault_plan``)
        every result comes back as :class:`ServedTokens` (== its plain
        list); otherwise the return is exactly the plain-list fast path."""
        if self.in_flight:
            raise RuntimeError(
                "run() on a batcher with streaming requests in flight: "
                "drain() first (run() owns all slots and indexes requests "
                "by position)"
            )
        if isinstance(max_new_tokens, (int, np.integer)):
            budgets = [int(max_new_tokens)] * len(requests)
        else:
            budgets = [int(b) for b in max_new_tokens]
        # ctor-level prefix_tokens: prompts carry the shared prefix and
        # are stripped to the part that actually prefills
        requests = [self._strip_prefix(r) for r in requests]
        # validate EVERYTHING before mutating any slot state: a mid-stream
        # raise would otherwise leave earlier admissions decoding, and a
        # reused batcher would hand their stale outputs to the next run's
        # colliding request ids
        _validate_workload(
            requests, budgets, prefill_width=self.prefill_width,
            prefix_len=self.prefix_len, decode_chunk=self.decode_chunk,
            ctx_size=self.config.ctx_size,
        )
        self._check_pool_capacity(budgets)
        if deadline_s is None:
            deadlines = {}
        elif isinstance(deadline_s, (int, float, np.floating, np.integer)):
            deadlines = {i: float(deadline_s) for i in range(len(requests))}
        else:
            if len(deadline_s) != len(requests):
                raise ValueError(
                    f"{len(deadline_s)} deadlines for {len(requests)} "
                    "requests"
                )
            deadlines = {i: float(d) for i, d in enumerate(deadline_s)
                         if d is not None}
        if any(d <= 0 for d in deadlines.values()):
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s!r}); a request "
                "that cannot start has no business being submitted"
            )
        stalls = (self.fault_plan is not None
                  and self.fault_plan.serve_timeout > 0)
        resilient = bool(deadlines) or self.poison_guard or stalls
        # deadline eviction needs a meaningful wall clock at chunk
        # boundaries, so those runs FENCE each chunk (EOS mode already
        # blocks per chunk for its token fetch — no extra fence there)
        fenced = bool(deadlines) or stalls
        self._deadlines = dict(deadlines)
        self._status = {}
        self._okrefs = {}
        finished: dict = {i: [] for i, b in enumerate(budgets) if b == 0}
        # longest-budget-first admission: the classic makespan heuristic —
        # big jobs start early, the tail is filled with small ones.  Output
        # order is by request id regardless.
        pending = sorted(
            ((i, r) for i, (r, b) in enumerate(zip(requests, budgets))
             if b > 0),
            key=lambda ir: -budgets[ir[0]],
        )
        # EOS mode: token VALUES drive scheduling (a stream may end any
        # step), so fetch once per chunk.  Budget mode (eos_id unset): the
        # whole admit/decode/recycle schedule is determined by the budgets
        # alone — stream every dispatch without ever blocking and resolve
        # the recorded refs in one fetch at the end.
        eos_mode = self.eos_id >= 0
        pending = [(rid, prompt, budgets[rid]) for rid, prompt in pending]
        telem = obs.enabled()
        if telem:
            t_run = time.perf_counter()
            self._req_ts.update(
                (rid, t_run) for rid, _p, _b in pending
            )
        with obs.span("serving.run", requests=len(requests),
                      mode="eos" if eos_mode else "budget"):
            while len(finished) < len(requests):
                self._sched_step += 1
                self._resume_parked()
                group = self._admit_from(pending)
                if group:
                    firsts = self._admit_group(group)
                    if eos_mode:
                        self._sync_admit_bookkeep(group, firsts)
                self._prefetch_ahead()
                self._harvest(finished, resolve=eos_mode)
                if fenced:
                    self._evict_expired(finished)
                active = [s for s, sl in enumerate(self.slots)
                          if not sl.free]
                if not active:
                    if (pending or self._parked) and self._quarantined:
                        # admission starved with every usable slot
                        # quarantined: scrub the poisoned rows and retry
                        self.scrub()
                    continue
                K = self.decode_chunk
                t_chunk = time.perf_counter() if fenced else 0.0
                out = self._dispatch_chunk(check=self.poison_guard)
                if self.poison_guard:
                    toks, ok_dev = out
                else:
                    toks, ok_dev = out, None
                if fenced:
                    # the fence deadlines pay for: wall clock at the
                    # chunk boundary now reflects completed device work
                    jax.block_until_ready(toks)
                    dt = time.perf_counter() - t_chunk
                    self._chunk_s = (0.8 * self._chunk_s + 0.2 * dt
                                     if self._chunk_s else dt)
                    prof = obs.profiler()
                    if prof is not None:
                        prof.record(
                            "serving.decode", seconds=dt,
                            occupancy=len(active), batch=self.max_batch,
                            chunk=K,
                            pages=(self._pool.pages_in_use
                                   if self._paged else 0))
                    cap = obs.capacity()
                    if cap is not None:
                        cap.observe("serving.decode", dt,
                                    occupancy=len(active),
                                    batch=self.max_batch, chunk=K)
                eager_guard = ok_dev is not None and (eos_mode or fenced)
                if eager_guard:
                    # eager containment (the per-chunk block is already
                    # paid for): evict BEFORE booking the chunk, so the
                    # garbage argmax stream never reaches the result
                    self._evict_poisoned(active, np.asarray(ok_dev),
                                         finished)
                    active = [s for s in active if not self.slots[s].free]
                if eos_mode:
                    self._sync_chunk_bookkeep(active, toks)
                else:
                    for s in active:
                        sl = self.slots[s]
                        use = min(K, sl.budget)
                        if use > 0:
                            sl.emitted.append((toks, s, use))
                            if ok_dev is not None and not eager_guard:
                                # deferred guard: flags resolved with the
                                # tokens in the end-of-run fetch
                                sl.ok_refs.append((ok_dev, s))
                            sl.budget -= use
                            self.stats["active_steps"] += use
                if fenced:
                    self._evict_expired(finished)
                self._harvest(finished, resolve=eos_mode)
            if not eos_mode:
                fetched: dict = {}  # shared across requests: chunk arrays
                for rid in list(finished):
                    refs = finished[rid]
                    if not refs:
                        continue
                    toks_l = self._resolve(refs, fetched)
                    okr = self._okrefs.pop(rid, None)
                    if okr:
                        # deferred poison guard (unfenced budget mode —
                        # the pipelining trade: detection is post-hoc, so
                        # truncate at the first bad chunk here; eager
                        # containment needs EOS mode or a deadline)
                        bad = None
                        for k, (arr, row) in enumerate(okr):
                            buf = fetched.get(id(arr))
                            if buf is None:
                                buf = fetched[id(arr)] = np.asarray(arr)
                            if not bool(buf[row]):
                                bad = k
                                break
                        if bad is not None:
                            cut = sum(c for _a, _i, c in refs[:bad + 1])
                            toks_l = toks_l[:cut]
                            self._status[rid] = "poisoned"
                            obs.inc("serving_poisoned_total")
                    finished[rid] = toks_l
                # the resolve fetch above was the run's ONE block — every
                # deferred request completed here
                self._obs_finish(list(self._req_ts))
        if telem:
            elapsed = time.perf_counter() - t_run
            nr_tokens = sum(len(v) for v in finished.values())
            obs.inc("serving_requests_total", len(requests))
            obs.inc("serving_tokens_total", nr_tokens)
            if elapsed > 0:
                obs.set_gauge("serving_tokens_per_sec",
                              nr_tokens / elapsed)
        self._deadlines = {}
        if resilient:
            return [ServedTokens(finished[i], self._status.get(i, "ok"))
                    for i in range(len(requests))]
        return [finished[i] for i in range(len(requests))]

    def _dispatch_chunk(self, check: bool = False):
        """One decode_chunk dispatch over all slots; updates cache/pos/
        tokens and the step telemetry, returns the (B, K) token array —
        or ``(tokens, ok)`` with the per-row all-finite chunk flags when
        ``check`` (the poison guard) is on.  Shared by run() and the
        streaming step()."""
        K = self.decode_chunk
        # dispatch-boundary span, unfenced: budget mode streams chunks
        # back-to-back and a block here would serialise the pipeline
        args = (self.params, self.cache, self.tokens, self.pos, self.pad)
        if self._paged:
            # the block tables are host numpy and the allocator mutates
            # them in place; jnp.asarray on CPU aliases the numpy buffer
            # zero-copy, so an in-flight async chunk would read tables the
            # host has already rewritten — ship an owned copy per chunk
            args = args + (jnp.asarray(self._tables.copy()),)
            if self._adapters is not None:
                # the adapter lane vector is host numpy the admission path
                # mutates — same owned-copy rule as the tables
                args = args + (jnp.asarray(self._adapter_vec.copy()),)
        with obs.span("serving.decode", chunk=K):
            with obs.step_annotation("serving.decode",
                                     self.stats["decode_steps"] // K):
                if check:
                    (self.cache, toks, self.pos, self.tokens,
                     ok) = self._decode(*args, nr=K, check=True)
                else:
                    self.cache, toks, self.pos, self.tokens = self._decode(
                        *args, nr=K,
                    )
        self.stats["decode_steps"] += K
        self.stats["slot_steps"] += self.max_batch * K
        if self._spill_on:
            for s, sl in enumerate(self.slots):
                if not sl.free:
                    self._slot_age[s] += 1
        if self._int8 and obs.enabled():
            # every decode step streams the resident quantized pages
            # through the in-kernel upcast; count the bytes so the
            # roofline attribution can see the dequant traffic
            pages_read = int((self._tables > 0).sum())
            obs.inc("serving_kv_dequant_bytes_total",
                    K * pages_read * self._page_qbytes)
        if self._paged and self.config.decode_impl == "fused":
            # each scan step ran the one-Pallas-program inner loop
            # (ops/fused_decode_step.py)
            obs.inc("serving_fused_decode_steps_total", K)
        return (toks, ok) if check else toks

    def _admit_from(self, pending: list) -> list:
        """Pop requests off ``pending`` into free slots; returns the
        admission group handed to _admit_group (empty if none).
        Quarantined slots (poison guard) stay out of rotation — their
        cache rows hold non-finite state a new request's decode would
        read through attention.

        With ``spill="host"`` a head-of-line request blocked on the pool
        first parks cold streams (:meth:`_make_room`) — freeing their
        lane AND their pages — so total in-flight streams can exceed both
        ``max_batch`` and what the device pool could hold at once."""
        if self._paged and self._spill_on and pending:
            self._make_room(self._pages_needed(pending[0][2]))
        free = [s for s, sl in enumerate(self.slots)
                if sl.free and s not in self._quarantined]
        group = []
        avail = self._pool.free_pages if self._paged else 0
        while pending and free:
            item = pending[0]
            rid, prompt, budget = item[0], item[1], item[2]
            tenant = item[3] if len(item) > 3 else 0
            if self._paged:
                need = self._pages_needed(budget)
                if need > avail:
                    # head-of-line blocking ON PURPOSE: skipping ahead to
                    # a smaller request would make the admission order
                    # (and so the whole trajectory) depend on pool timing
                    break
                avail -= need
            s = free[0]
            if self._adapters is not None and tenant:
                acq = self._adapters.acquire(tenant)
                if acq is None:
                    # every adapter slot busy or pinned: head-of-line
                    # wait, exactly like a pool-page deficit
                    break
                pslot, entry = acq
                if entry is not None:
                    # residency miss: re-fetch the factors from the host
                    # store and install them into the stack slot the pool
                    # just freed (possibly evicting a cold tenant) —
                    # BEFORE the admit dispatch reads self.params
                    adapter, scale, _r = entry
                    self.params = lora.install_adapter(
                        self.params, pslot, adapter, scale)
                self._adapter_vec[s] = pslot
                self._slot_tenant[s] = tenant
            pending.pop(0)
            free.pop(0)
            group.append((s, rid, prompt, budget))
        return group

    def _sync_admit_bookkeep(self, group, firsts):
        """Fetch an admission group's first tokens (one round trip per
        group) and install host-int bookkeeping — the synchronous
        discipline EOS mode and the streaming interface share."""
        firsts_h = np.asarray(firsts)
        for g, (s, _rid, _p, _b) in enumerate(group):
            sl = self.slots[s]
            first_i = int(firsts_h[g])
            sl.emitted = [first_i]
            sl.done_eos = self.eos_id >= 0 and first_i == self.eos_id

    def _sync_chunk_bookkeep(self, active, toks, chunk_t0=None):
        """Fetch one decode chunk's tokens and append them to each active
        slot up to its budget / EOS (host-int bookkeeping).  ``chunk_t0``
        (the dispatch-entry perf_counter, streaming path only) times the
        whole chunk through its sync point here for request traces."""
        toks_host = jax.device_get(toks)
        rt = obs.reqtrace()
        secs = (time.perf_counter() - chunk_t0
                if rt is not None and chunk_t0 is not None else 0.0)
        for s in active:
            sl = self.slots[s]
            booked = 0
            for j in range(toks_host.shape[1]):
                if sl.budget <= 0 or sl.done_eos:
                    break
                self.stats["active_steps"] += 1
                tok = int(toks_host[s, j])
                sl.emitted.append(tok)
                sl.budget -= 1
                booked += 1
                if tok == self.eos_id:
                    sl.done_eos = True
            if rt is not None and booked:
                rt.note(sl.request_id, "decode",
                        replica=getattr(self, "_replica_ix", None),
                        seconds=secs, tokens=booked,
                        emitted=len(sl.emitted))

    # -- multi-tenant adapters (adapter_slots > 0) ------------------------

    def register_adapter(self, tenant, adapter, scale: float = 1.0,
                         round_ix=None) -> None:
        """(Re)register ``tenant``'s LoRA factors (``slice_adapter`` wire
        format) in the host store; if the tenant is currently RESIDENT
        the new version is hot-swapped into its stack slot in place (the
        single-replica flow — fleets roll new versions through the
        rollout plane instead, which rebuilds replicas from pushed
        params)."""
        if self._adapters is None:
            raise ValueError(
                "register_adapter: this batcher has no adapter pool "
                "(pass adapter_slots= to the ctor)")
        self._adapters.put(tenant, adapter, scale, round_ix)
        pslot = self._adapters.slot_of(tenant)
        if pslot is not None:
            self.params = lora.install_adapter(
                self.params, pslot, adapter, scale)

    def adapter_resident(self, tenant) -> bool:
        """Whether ``tenant``'s adapter is installed in this batcher's
        stacks right now — the router's tenant-affinity signal (tenant 0,
        the null adapter, is always resident)."""
        if int(tenant) == 0:
            return True
        return self._adapters is not None and self._adapters.resident(
            int(tenant))

    def _obs_adapters(self):
        """Per-tier adapter residency gauges, mirroring the KV pool's:
        ``tier="device"`` counts installed stack slots, ``tier="host"``
        the store entries a miss can re-fetch."""
        if self._adapters is not None and obs.enabled():
            obs.set_gauge("serving_adapter_resident",
                          len(self._adapters.resident_tenants),
                          tier="device")
            obs.set_gauge("serving_adapter_resident",
                          len(self._adapters.store), tier="host")

    # -- streaming interface (requests arrive over time) ------------------

    @property
    def in_flight(self) -> int:
        """Requests submitted but not yet returned by step()/drain() —
        parked (spilled) streams included: they hold no lane or device
        pages, but they are very much still being served."""
        active = sum(1 for sl in self.slots if not sl.free)
        return (len(self._queue) + len(self._instant) + active
                + len(self._parked))

    def submit(self, rid, prompt, max_new_tokens: int,
               deadline_s: float | None = None,
               adapter_id=0) -> None:
        """Enqueue one request under key ``rid`` (any hashable, unique
        among in-flight requests); it joins the running batch at the next
        ``step()`` with a free slot.  Zero budgets resolve to ``[]`` at
        the next step.

        With ``max_queue`` set, a full waiting queue raises
        :class:`AdmissionRejected` (with a ``retry_after_s`` backoff
        estimate from recent chunk times) instead of growing without
        bound — load the caller can see beats latency it can't.
        ``deadline_s`` bounds the request's decode time from admission;
        past it the slot is evicted and the partial stream comes back as
        :class:`ServedTokens` with status ``timed_out``.

        ``adapter_id`` (multi-tenant batchers, ``adapter_slots > 0``)
        names the tenant whose LoRA adapter decodes this request; 0 is
        the null adapter (bitwise the base model).  Non-zero tenants must
        be registered (:meth:`register_adapter` or the shared store)
        before submit; a non-resident tenant's admission waits for an
        adapter slot exactly like it waits for KV pages."""
        adapter_id = int(adapter_id)
        if adapter_id:
            if self._adapters is None:
                raise ValueError(
                    f"adapter_id={adapter_id}: this batcher has no "
                    "adapter pool (pass adapter_slots= to the ctor)")
            if not (self._adapters.resident(adapter_id)
                    or adapter_id in self._adapters.store):
                raise KeyError(
                    f"adapter_id {adapter_id} is not registered "
                    "(register_adapter() it first)")
        if (rid in self._instant
                or any(q[0] == rid for q in self._queue)
                or any(sl.request_id == rid for sl in self.slots
                       if not sl.free)):
            raise ValueError(f"request id {rid!r} already in flight")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s={deadline_s} must be > 0")
        if (self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            # backoff estimate: one queue lane frees up roughly every
            # (chunk time x queue depth / batch width) at steady state
            est = self._chunk_s if self._chunk_s > 0 else 0.05
            retry_after = max(0.01, est * (1 + len(self._queue)
                                           / self.max_batch))
            self._reject(
                "queue_full",
                f"queue full ({len(self._queue)}/{self.max_queue}); "
                f"retry in ~{retry_after:.3f}s", retry_after,
            )
        budget = int(max_new_tokens)
        prompt = self._strip_prefix(prompt)
        _validate_workload(
            [prompt], [budget], prefill_width=self.prefill_width,
            prefix_len=self.prefix_len, decode_chunk=self.decode_chunk,
            ctx_size=self.config.ctx_size,
        )
        self._check_pool_capacity([budget], label=f"request {rid!r}")
        if self.slo_deadline_s is not None and budget > 0:
            obs.set_gauge("serving_slo_deadline_s", self.slo_deadline_s)
            wait, bound = self._admission_wait_estimate(budget)
            if wait > self.slo_deadline_s:
                retry_after = max(0.01, wait - self.slo_deadline_s)
                self._reject(
                    bound,
                    f"request {rid!r} would miss the "
                    f"{self.slo_deadline_s}s admission SLO (estimated "
                    f"wait ~{wait:.3f}s, bound by {bound}); retry in "
                    f"~{retry_after:.3f}s", retry_after,
                )
        rt = obs.reqtrace()
        if obs.enabled() or rt is not None:
            self._req_ts[rid] = time.perf_counter()
        if rt is not None:
            rt.note(rid, "submit",
                    replica=getattr(self, "_replica_ix", None),
                    tokens=len(prompt), budget=budget,
                    tenant=adapter_id)
        if deadline_s is not None:
            self._deadlines[rid] = float(deadline_s)
        if budget == 0:
            self._instant[rid] = []
            return
        if self._prefix_tokens is not None:
            self._hit_rids.add(rid)
        self._queue.append((rid, list(prompt), budget, adapter_id))

    def step(self) -> dict:
        """Admit queued requests into free slots, decode ONE chunk, and
        return ``{rid: tokens}`` for every request that finished.

        The streaming discipline is synchronous (one token fetch per
        chunk — the minimum latency path); a workload known up front is
        faster through ``run()`` (pipelined dispatch) or ``serve_fused``
        (one program)."""
        finished: dict = dict(self._instant)
        self._instant.clear()
        self._obs_finish(list(finished))  # zero-budget instants
        self._sched_step += 1
        self._resume_parked()
        if self._deadlines or self._hit_rids:
            # SLO-driven admission order: tightest deadline slack first
            # (the clock starts at admission, so a request's slack IS its
            # deadline budget), prefix hits before misses at equal slack
            # (they skip prefill work — cheaper to start).  The sort is
            # stable, so with neither signal set this is plain FIFO and
            # the pre-SLO trajectories are unchanged.
            inf = float("inf")
            self._queue.sort(key=lambda q: (
                self._deadlines.get(q[0], inf),
                0 if q[0] in self._hit_rids else 1,
            ))
        group = self._admit_from(self._queue)
        if group:
            prof = obs.profiler()
            t_admit = time.perf_counter() if prof is not None else 0.0
            self._sync_admit_bookkeep(group, self._admit_group(group))
            if prof is not None:
                prof.record(
                    "serving.prefill",
                    seconds=time.perf_counter() - t_admit,
                    group=len(group),
                    tokens=sum(len(p) for _s, _r, p, _b in group),
                    width=self.prefill_width,
                    pages=self._pool.pages_in_use if self._paged else 0)
        self._prefetch_ahead()
        self._harvest(finished, resolve=True)
        self._evict_expired(finished)
        active = [s for s, sl in enumerate(self.slots) if not sl.free]
        if (not active and (self._queue or self._parked)
                and self._quarantined):
            # every usable slot quarantined while requests wait: scrub
            # the poisoned rows so the next step can admit
            self.scrub()
        if active:
            t_chunk = time.perf_counter()
            out = self._dispatch_chunk(check=self.poison_guard)
            if self.poison_guard:
                toks, ok_dev = out
                # the streaming path blocks on toks right below anyway
                self._evict_poisoned(active, np.asarray(ok_dev), finished)
                active = [s for s in active if not self.slots[s].free]
            else:
                toks = out
            self._sync_chunk_bookkeep(active, toks, chunk_t0=t_chunk)
            dt = time.perf_counter() - t_chunk
            self._chunk_s = (0.8 * self._chunk_s + 0.2 * dt
                             if self._chunk_s else dt)
            prof = obs.profiler()
            if prof is not None:
                prof.record(
                    "serving.decode", seconds=dt,
                    occupancy=len(active), batch=self.max_batch,
                    chunk=self.decode_chunk,
                    pages=self._pool.pages_in_use if self._paged else 0)
            cap = obs.capacity()
            if cap is not None:
                cap.observe("serving.decode", dt,
                            occupancy=len(active), batch=self.max_batch,
                            chunk=self.decode_chunk)
            self._harvest(finished, resolve=True)
            self._evict_expired(finished)
        if finished and obs.enabled():
            obs.inc("serving_requests_total", len(finished))
            obs.inc("serving_tokens_total",
                    sum(len(v) for v in finished.values()))
        if obs.enabled():
            # the queue-depth series the autoscaler and the burn-rate
            # monitors window over (one sample per decode chunk)
            obs.set_gauge("serving_queue_depth",
                          len(self._queue) + len(self._instant))
            self._obs_adapters()
        obs.record_samples()
        # tag evicted requests (their partial streams still compare equal
        # to the same plain list); clean completions stay plain lists
        for rid in list(finished):
            status = self._status.pop(rid, None)
            if status is not None:
                finished[rid] = ServedTokens(finished[rid], status)
        return finished

    def drain(self) -> dict:
        """step() until every in-flight request has finished; returns all
        their outputs."""
        out: dict = {}
        while self.in_flight:
            out.update(self.step())
        return out


# -- fully fused serving: the whole workload in ONE dispatch ---------------


def _lane_insert(cache, staged, mask, ix, B):
    """Masked lane-aligned cache insert shared by every fused admitter:
    lane b takes staged row ix[b] where mask[b], keeps its state
    otherwise — jnp.where selects, no per-slot conds, no
    dynamic_update_slice."""

    def sel(big, st):
        s = st[ix].astype(big.dtype)
        m = mask.reshape((B,) + (1,) * (big.ndim - 1))
        return jnp.where(m, s, big)

    return jax.tree.map(sel, cache, staged)


def _admit_bookkeeping(nxt, slot_req, slot_budget, out, out_n, budgets,
                       firsts, eos_id: int, N: int):
    """The slot bookkeeping every fused admitter shares (ONE copy — the
    plain and speculative schedulers' admission semantics must not
    drift): pack waiting requests into free lanes (free lane b takes
    request nxt + #free lanes before b), write each admitted request's
    prefill token to its output row, zero the budget of a request whose
    FIRST token is already EOS.  Returns (mask, ix) for the caller's own
    lane-state updates plus the advanced bookkeeping."""
    free = slot_req < 0
    offset = jnp.cumsum(free.astype(jnp.int32)) - free
    req = nxt + offset
    mask = free & (req < N)
    ix = jnp.where(mask, req, 0)
    out = out.at[jnp.where(mask, req, N), 0].set(
        firsts[ix].astype(out.dtype)
    )
    done = (firsts[ix] == eos_id) if eos_id >= 0 \
        else jnp.zeros_like(mask)
    slot_budget = jnp.where(
        mask, jnp.where(done, 0, budgets[ix] - 1), slot_budget
    )
    slot_req = jnp.where(mask, req, slot_req)
    out_n = jnp.where(mask, 1, out_n)
    nxt = nxt + jnp.minimum(free.sum(), N - nxt)
    return mask, ix, slot_req, slot_budget, out, out_n, nxt


def _pack_workload(requests, budgets, prefill_width: int):
    """Host-side workload packing shared by the fused entry points (the
    two fused servers must compile identical program variants for the
    same workload): longest-budget-first (the host scheduler's makespan
    heuristic), N padded to the next power of two with budget-1 dummy
    requests (they briefly occupy tail slots — harmless), cap to a
    multiple of 16.  Returns (live, N, cap, prompts, lengths, budg) or
    None when nothing has a positive budget."""
    live = [(i, r, b) for i, (r, b) in enumerate(zip(requests, budgets))
            if b > 0]
    if not live:
        return None
    live.sort(key=lambda irb: -irb[2])
    N0 = len(live)
    N = 1 << (N0 - 1).bit_length()
    cap = -(-max(budgets) // 16) * 16
    prompts = np.zeros((N, prefill_width), np.int32)
    lengths = np.ones((N,), np.int32)
    budg = np.ones((N,), np.int32)
    for g, (_i, r, b) in enumerate(live):
        prompts[g, :len(r)] = r
        lengths[g] = len(r)
        budg[g] = b
    prompts[N0:, 0] = 1  # dummy one-token prompts, budget 1
    return live, N, cap, prompts, lengths, budg


def _gather_results(out, live, nr_requests: int):
    """Per-request rows back from a fused (N, cap) output buffer: row g
    belongs to live[g], trimmed to its budget (zeros past EOS ARE the
    result — generate()'s pad semantics)."""
    results: list = [[] for _ in range(nr_requests)]
    for g, (i, _r, b) in enumerate(live):
        results[i] = [int(t) for t in out[g, :b]]
    return results


def _obs_fused_done(t0: float, results, live):
    """Telemetry tail shared by the fused entry points (caller checks
    ``obs.enabled()``): a fused run is one dispatch + one fetch, so every
    live request completes AT the fetch — each observes the same
    end-to-end latency, and tokens/sec is the workload total over it."""
    elapsed = time.perf_counter() - t0
    nr_tokens = sum(len(r) for r in results)
    obs.inc("serving_requests_total", len(results))
    obs.inc("serving_tokens_total", nr_tokens)
    for _ in live:
        obs.observe("serving_request_seconds", elapsed)
    if elapsed > 0:
        obs.set_gauge("serving_tokens_per_sec", nr_tokens / elapsed)


@functools.lru_cache(maxsize=8)
def _fused_program(config: LlamaConfig, max_batch: int, prefill_width: int,
                   prefix_len: int, decode_chunk: int, eos_id: int,
                   cap: int, nr_requests: int):
    """Compile the entire continuous-batching schedule into one program.

    Token-dependent control flow (EOS can end any stream at any step)
    means the schedule can't be precomputed like the budget-mode scan
    (:func:`_scheduled_program`) — so a ``lax.while_loop`` runs it ALL on
    device: each iteration admits into every free slot via ONE masked
    vmapped prefill (lane-aligned ``jnp.where`` select into the cache —
    no per-slot conds, no dynamic_update_slice), then decodes a
    ``decode_chunk``-step scan whose emitted tokens land in the output
    buffer with one (B, K) scatter per chunk.  EOS is detected on device
    (budget zeroed at the EOS step; later columns stay 0 — generate()'s
    pad semantics).  One dispatch, one fetch, zero mid-run host
    involvement.

    ``nr_requests`` and ``cap`` (output columns) are trace-time shapes;
    :func:`serve_fused` pads both to coarse buckets so program variants
    stay bounded."""
    cfg = dataclasses.replace(config, decode=True)
    model = Llama(cfg)
    W, P, B, K, N = (prefill_width, prefix_len, max_batch, decode_chunk,
                     nr_requests)
    _prefill_one = functools.partial(_right_aligned_prefill, model, W, P)

    @jax.jit
    def serve(params, prompts, lengths, budgets, prefix_cache=None):
        """prompts (N, W) right-padded; budgets (N,) >= 1.
        -> out (N, cap): row i = request i's emitted tokens (col 0 = the
        prefill token), zero-padded past its budget / EOS."""
        # serving cache built IN-TRACE (shape-only; the probe forward is
        # DCE'd) — a separate host-side eval_shape cost 0.7 s per call
        cache0 = _empty_cache_of(model, B, params)
        # stage ALL prefills up front in ONE vmapped N-way batch (the
        # whole workload is known — that's serve_fused's contract), so
        # admission inside the loop is a cheap row gather + select.  The
        # first masked-vmapped design re-prefilled every free lane at
        # every admission boundary: ~3x the prefill compute of the
        # requests themselves at bench shapes (measured round 5).
        row_caches, firsts, pads = jax.vmap(
            _prefill_one, in_axes=(None, 0, 0, None)
        )(params, prompts, lengths, prefix_cache)
        staged = jax.tree.map(lambda a: jnp.squeeze(a, axis=1), row_caches)

        def admit_all(state):
            """Fill every free slot from the staging buffer
            (:func:`_admit_bookkeeping` + this scheduler's lane state)."""
            (cache, tokens, pos, pad, slot_req, slot_budget, out, out_n,
             nxt) = state
            mask, ix, slot_req, slot_budget, out, out_n, nxt = \
                _admit_bookkeeping(nxt, slot_req, slot_budget, out, out_n,
                                   budgets, firsts, eos_id, N)
            cache = _lane_insert(cache, staged, mask, ix, B)
            tokens = jnp.where(mask, firsts[ix], tokens)
            pos = jnp.where(mask, P + W, pos)
            pad = jnp.where(mask, pads[ix], pad)
            return (cache, tokens, pos, pad, slot_req, slot_budget, out,
                    out_n, nxt)

        def chunk(state):
            (cache, tokens, pos, pad, slot_req, slot_budget, out, out_n,
             nxt) = state
            (cache, tokens, pos), toks = jax.lax.scan(
                functools.partial(_decode_step, model, P, params, pad),
                (cache, tokens, pos), None, length=K,
            )
            T = toks.T  # (B, K)
            steps = jnp.arange(K)[None, :]
            if eos_id >= 0:
                # a row is live until its budget runs out OR a PRIOR step
                # hit EOS (the EOS step itself is written — generate()'s
                # keep-EOS semantics)
                is_eos = T == eos_id
                prior_eos = (jnp.cumsum(is_eos, axis=1) - is_eos) > 0
                live = (steps < slot_budget[:, None]) & ~prior_eos
                eos_in_live = jnp.any(is_eos & live, axis=1)
            else:
                live = steps < slot_budget[:, None]
                eos_in_live = jnp.zeros((B,), bool)
            used = live.sum(axis=1)
            rows = jnp.where(live, slot_req[:, None], N)
            cols = jnp.minimum(out_n[:, None] + steps, cap - 1)
            out = out.at[rows, cols].set(T.astype(out.dtype))
            out_n = out_n + used
            slot_budget = jnp.where(eos_in_live, 0, slot_budget - used)
            # recycle finished slots at the chunk boundary (same as the
            # host scheduler: mid-chunk finishers idle to the boundary)
            slot_req = jnp.where(slot_budget > 0, slot_req, -1)
            return (cache, tokens, pos, pad, slot_req, slot_budget, out,
                    out_n, nxt)

        def body(state):
            slot_req, nxt = state[4], state[8]
            state = jax.lax.cond(
                jnp.any(slot_req < 0) & (nxt < N), admit_all,
                lambda s: s, state,
            )
            return chunk(state)

        def cond(state):
            slot_budget, nxt = state[5], state[8]
            return (nxt < N) | jnp.any(slot_budget > 0)

        state = (
            cache0,
            jnp.zeros((B,), jnp.int32),      # tokens
            jnp.zeros((B,), jnp.int32),      # pos
            jnp.zeros((B,), jnp.int32),      # pad
            jnp.full((B,), -1, jnp.int32),   # slot_req (-1 = free)
            jnp.zeros((B,), jnp.int32),      # slot_budget
            jnp.zeros((N + 1, cap), jnp.int32),  # out (+ dump row N)
            jnp.zeros((B,), jnp.int32),      # out_n (per-slot col cursor)
            jnp.int32(0),                    # next_req
        )
        state = jax.lax.while_loop(cond, body, state)
        return state[6][:N]

    return serve, _make_empty_cache(model, max_batch)


def _plan_schedule(budgets, B: int, K: int):
    """Host-side planner for budget-mode fused serving: simulate the slot
    scheduler (admit into free slots at each chunk boundary, decode up to
    ``K`` steps per active slot, retire at boundaries) over ``budgets``
    (live requests, table order) and return the per-chunk numpy tables the
    scheduled scan consumes.  Mirrors the while_loop scheduler exactly —
    the whole point: with no EOS the schedule depends only on budgets, so
    the device program needs no scalar feedback at all.

    Returns (admit_req, use, out_row, out_col), each (C, B) int32:
    admit_req[c,b] = request admitted into lane b before chunk c (-1 =
    none); use[c,b] = live decode steps for lane b in chunk c; out_row /
    out_col = output buffer row (len(budgets) = dump row) and start
    column for lane b's chunk-c tokens."""
    N = len(budgets)
    slot_budget = [0] * B
    slot_req = [-1] * B
    slot_col = [0] * B
    nxt = 0
    admit_req, use, out_row, out_col = [], [], [], []
    while nxt < N or any(b > 0 for b in slot_budget):
        ar = [-1] * B
        for b in range(B):
            if slot_budget[b] <= 0 and nxt < N:
                ar[b] = nxt
                slot_req[b] = nxt
                slot_budget[b] = budgets[nxt] - 1  # prefill emits token 0
                slot_col[b] = 1
                nxt += 1
        u, row, col = [0] * B, [N] * B, [0] * B
        for b in range(B):
            if slot_budget[b] > 0:
                u[b] = min(K, slot_budget[b])
                row[b] = slot_req[b]
                col[b] = slot_col[b]
                slot_col[b] += u[b]
                slot_budget[b] -= u[b]
        admit_req.append(ar)
        use.append(u)
        out_row.append(row)
        out_col.append(col)
    return tuple(
        np.asarray(t, np.int32).reshape(-1, B)
        for t in (admit_req, use, out_row, out_col)
    )


@functools.lru_cache(maxsize=8)
def _scheduled_program(config: LlamaConfig, max_batch: int,
                       prefill_width: int, prefix_len: int,
                       decode_chunk: int, nr_requests: int,
                       nr_chunks: int):
    """Budget-mode fused serving as a ``lax.scan`` over a precomputed
    schedule.

    The while_loop variant (:func:`_fused_program`) must do its own
    scheduling on device because EOS is token-dependent.  Here the host
    has already planned everything (:func:`_plan_schedule`), so the
    device program is pure compute: ONE N-way vmapped prefill up front
    (staged row caches), then a scan over chunks — a single ``lax.cond``
    (did ANY lane admit this chunk?) around a lane-aligned gather/select
    admission, followed by ``decode_chunk`` plain decode steps.  No
    output buffer, no scatters, no scalar bookkeeping on device at all:
    the raw (C, B, K) token tensor comes back as scan ys and the HOST —
    which planned which (chunk, lane, step) belongs to which request —
    assembles the per-request outputs in numpy.  Static trip count,
    maximal XLA pipelining, one dispatch, one fetch."""
    cfg = dataclasses.replace(config, decode=True)
    model = Llama(cfg)
    W, P, B, K, N = (prefill_width, prefix_len, max_batch, decode_chunk,
                     nr_requests)
    del nr_chunks  # shapes the admit_req table; part of the cache key
    _prefill_one = functools.partial(_right_aligned_prefill, model, W, P)

    @jax.jit
    def serve(params, prompts, lengths, admit_req,
              prefix_cache=None):
        """prompts (N, W) right-padded; admit_req (C, B);
        -> (firsts (N,), toks (C, B, K))."""
        # in-trace shape-only cache init (see _fused_program)
        cache0 = _empty_cache_of(model, B, params)
        row_caches, firsts, pads = jax.vmap(
            _prefill_one, in_axes=(None, 0, 0, None)
        )(params, prompts, lengths, prefix_cache)
        staged = jax.tree.map(lambda a: jnp.squeeze(a, axis=1), row_caches)

        def chunk(carry, areq):
            cache, tokens, pos, pad = carry

            def admit(args):
                cache, tokens, pos, pad = args
                mask = areq >= 0
                ix = jnp.maximum(areq, 0)
                cache = _lane_insert(cache, staged, mask, ix, B)
                tokens = jnp.where(mask, firsts[ix], tokens)
                pos = jnp.where(mask, P + W, pos)
                pad = jnp.where(mask, pads[ix], pad)
                return cache, tokens, pos, pad

            cache, tokens, pos, pad = jax.lax.cond(
                jnp.any(areq >= 0), admit, lambda a: a,
                (cache, tokens, pos, pad),
            )
            (cache, tokens, pos), toks = jax.lax.scan(
                functools.partial(_decode_step, model, P, params, pad),
                (cache, tokens, pos), None, length=K,
            )
            return (cache, tokens, pos, pad), toks.T  # (B, K)

        carry0 = (
            cache0,
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32),
        )
        _, toks = jax.lax.scan(chunk, carry0, admit_req)
        return firsts, toks  # (N,), (C, B, K)

    return serve, _make_empty_cache(model, max_batch)


def serve_fused(config: LlamaConfig, params, requests, max_new_tokens, *,
                max_batch: int = 8, prefill_width: int = 64,
                eos_id: int | None = None, decode_chunk: int = 1,
                prefix: tuple | None = None):
    """One-dispatch continuous batching: same contract and BIT-identical
    outputs as ``ContinuousBatcher.run`` (oracle: tests/test_serving.py),
    but the whole admit/decode/recycle schedule executes on device.

    Budget mode (``eos_id`` unset) plans the complete schedule host-side
    and runs it as a table-driven ``lax.scan`` (:func:`_scheduled_program`
    — no on-device scheduling at all); EOS mode needs token-dependent
    control flow, so it runs the on-device ``lax.while_loop`` scheduler
    (:func:`_fused_program`).

    Use this when the host<->device link is slow (remote tunnels, congested
    PCIe) or the workload is known up front; use ``ContinuousBatcher`` when
    requests arrive over time or you need token streaming.

    Numerical caveat: bit-identity across serving paths assumes they run
    the SAME attention implementation.  The flash-decode kernel
    (``decode_impl='flash'``) and the einsum path reduce in different
    orders — last-ulp logit differences can flip an argmax near a tie, so
    parity ACROSS ``decode_impl`` settings is checked empirically (the
    TPU A/B in ``examples/bench_speculative.py --serve``), not
    guaranteed.  Within one ``decode_impl`` the oracle tests pin exact
    equality."""
    if config.decode_seq_shards > 1:
        raise NotImplementedError(
            "fused serving over the sequence-sharded cache: use one "
            "server per replica today"
        )
    config = config.with_resolved_decode_impl(params)
    prefix_cache, prefix_len = prefix if prefix is not None else (None, 0)
    if isinstance(max_new_tokens, (int, np.integer)):
        budgets = [int(max_new_tokens)] * len(requests)
    else:
        budgets = [int(b) for b in max_new_tokens]
    eos = -1 if eos_id is None else int(eos_id)
    if decode_chunk < 1:
        raise ValueError(f"decode_chunk must be >= 1, got {decode_chunk}")
    worst = max(budgets, default=0)
    _validate_workload(requests, budgets, prefill_width=prefill_width,
                       prefix_len=prefix_len, decode_chunk=decode_chunk,
                       ctx_size=config.ctx_size)
    packed = _pack_workload(requests, budgets, prefill_width)
    if packed is None:
        return [[] for _ in requests]
    live, N, cap, prompts, lengths, budg = packed
    telem = obs.enabled()
    t0 = time.perf_counter() if telem else 0.0
    if eos < 0:
        # budget mode: plan on host, execute one table-driven scan.  The
        # chunk count C is exact — a padded no-op chunk would cost K full
        # decode steps (up to 40% waste measured at K=32), far more than
        # the occasional recompile for a new C; the lru cache bounds
        # program variants either way.
        admit_req, use, out_row, _out_col = _plan_schedule(
            [int(b) for b in budg], max_batch, decode_chunk
        )
        C = admit_req.shape[0]
        serve, _ = _scheduled_program(
            config, max_batch, prefill_width, prefix_len, decode_chunk,
            N, C,
        )
        # span covers dispatch AND the fetch below (np.asarray blocks), so
        # wall time is the true end-to-end serve time — no extra fence
        with obs.span("serving.fused", requests=len(live), mode="budget",
                      chunks=int(C)):
            firsts, toks = serve(
                params, jnp.asarray(prompts), jnp.asarray(lengths),
                jnp.asarray(admit_req), prefix_cache,
            )
            # host assembly from the planner's own tables: the device
            # returned pure compute (firsts + the raw (C, B, K) token
            # tensor); which (chunk, lane, step) belongs to which request
            # is host knowledge
            firsts, toks = np.asarray(firsts), np.asarray(toks)
        by_req: list = [[] for _ in range(N)]
        for g in range(N):
            by_req[g].append(int(firsts[g]))
        for c in range(C):
            for b in range(max_batch):
                r = out_row[c, b]
                if r < N and use[c, b] > 0:
                    by_req[r].extend(int(t) for t in toks[c, b, :use[c, b]])
        results: list = [[] for _ in requests]
        for g, (i, _r, b) in enumerate(live):
            results[i] = by_req[g]
        if telem:
            _obs_fused_done(t0, results, live)
        return results
    serve, _ = _fused_program(
        config, max_batch, prefill_width, prefix_len, decode_chunk, eos,
        cap, N,
    )
    with obs.span("serving.fused", requests=len(live), mode="eos"):
        out = np.asarray(serve(
            params, jnp.asarray(prompts), jnp.asarray(lengths),
            jnp.asarray(budg), prefix_cache,
        ))
    # EOS semantics need no host pass: each request owns its buffer row,
    # the device stops writing at the EOS, and the zeros past it are
    # exactly generate()'s pad
    results = _gather_results(out, live, len(requests))
    if telem:
        _obs_fused_done(t0, results, live)
    return results


# -- fused speculative serving: continuous batching x draft+verify ---------


@functools.lru_cache(maxsize=8)
def _fused_spec_program(target_config: LlamaConfig,
                        draft_config: LlamaConfig, max_batch: int,
                        prefill_width: int, gamma: int, eos_id: int,
                        cap: int, nr_requests: int):
    """Compile continuous batching WITH speculative decoding into one
    program: the :func:`_fused_program` while_loop scheduler whose body
    unit is a draft+verify round (models/speculative.py) instead of a
    plain decode chunk.

    Per iteration, every lane runs the draft's 2-token catch-up +
    ``gamma - 1`` single-token steps, ONE (gamma+1)-window target verify,
    and commits its accepted prefix + correction — so a lane at
    acceptance ``a`` emits ``a+1`` tokens per target pass, and the slot
    machinery (admission into free lanes, budgets, EOS, recycling) rides
    the same masked lane-select design.  Greedy only: every emitted token
    is the target's own greedy continuation whatever the draft, so the
    per-request outputs are BIT-IDENTICAL to solo ``generate()`` — the
    oracle that pins the whole scheduler.

    Lane state is O(1) per lane: no token ring buffer — the draft
    catch-up needs only the last TWO committed tokens (a rolling pair),
    and committed output goes straight to the (N, cap) output buffer.
    """
    tcfg = dataclasses.replace(target_config, decode=True)
    dcfg = dataclasses.replace(draft_config, decode=True)
    target, draft = Llama(tcfg), Llama(dcfg)
    W, B, N, G = (prefill_width, max_batch, nr_requests, gamma)
    _t_prefill = functools.partial(_right_aligned_prefill, target, W, 0)
    _d_prefill = functools.partial(_right_aligned_prefill, draft, W, 0)

    @jax.jit
    def serve(tparams, dparams, prompts, lengths, budgets):
        """prompts (N, W) right-padded; budgets (N,) >= 1.
        -> out (N, cap): row i = request i's emitted tokens (col 0 = the
        prefill token), zero-padded past its budget / EOS."""
        tcache0 = _empty_cache_of(target, B, tparams)
        dcache0 = _empty_cache_of(draft, B, dparams)
        t_rows, firsts, pads = jax.vmap(
            _t_prefill, in_axes=(None, 0, 0, None)
        )(tparams, prompts, lengths, None)
        d_rows, _, _ = jax.vmap(
            _d_prefill, in_axes=(None, 0, 0, None)
        )(dparams, prompts, lengths, None)
        t_staged = jax.tree.map(lambda a: jnp.squeeze(a, axis=1), t_rows)
        d_staged = jax.tree.map(lambda a: jnp.squeeze(a, axis=1), d_rows)
        # the draft catch-up window [L-2, L) after admission covers the
        # LAST PROMPT TOKEN (right-aligned: slot W-1) and the first
        # generated token
        lasts = jnp.take_along_axis(
            prompts, (lengths - 1)[:, None], axis=1
        )[:, 0]

        def admit_all(state):
            (tcache, dcache, pair, L, pad, slot_req, slot_budget, out,
             out_n, nxt, n_prop, n_acc) = state
            mask, ix, slot_req, slot_budget, out, out_n, nxt = \
                _admit_bookkeeping(nxt, slot_req, slot_budget, out, out_n,
                                   budgets, firsts, eos_id, N)
            tcache = _lane_insert(tcache, t_staged, mask, ix, B)
            dcache = _lane_insert(dcache, d_staged, mask, ix, B)
            pair = jnp.where(
                mask[:, None],
                jnp.stack([lasts[ix], firsts[ix]], axis=1), pair,
            )
            L = jnp.where(mask, W + 1, L)
            pad = jnp.where(mask, pads[ix], pad)
            return (tcache, dcache, pair, L, pad, slot_req, slot_budget,
                    out, out_n, nxt, n_prop, n_acc)

        def spec_round(state):
            (tcache, dcache, pair, L, pad, slot_req, slot_budget, out,
             out_n, nxt, n_prop, n_acc) = state
            # --- draft: catch-up + gamma-1 steps (speculative.py body,
            # greedy, pair-fed) --------------------------------------
            cpos = (L - 2)[:, None] + jnp.arange(2)[None, :]
            clog, dv = draft.apply(
                {**dparams, "cache": dcache},
                pair, positions=cpos, pad=pad, mutable=["cache"],
            )
            dcache = dv["cache"]
            p1 = jnp.argmax(clog[:, -1], axis=-1).astype(pair.dtype)
            # gamma-1 plain draft steps: the ONE shared copy of the decode
            # math (_decode_step) — bit-parity with every other serving
            # path rests on it
            (dcache, _, _), rest = jax.lax.scan(
                functools.partial(_decode_step, draft, 0, dparams, pad),
                (dcache, p1, L), None, length=G - 1,
            )
            props = jnp.concatenate([p1[:, None], rest.T], axis=1)  # (B,G)
            # --- verify: one (gamma+1)-window target forward --------
            win = jnp.concatenate([pair[:, 1:], props], axis=1)
            pos = (L - 1)[:, None] + jnp.arange(G + 1)[None, :]
            t_logits, tv = target.apply(
                {**tparams, "cache": tcache},
                win, positions=pos, pad=pad, mutable=["cache"],
            )
            tcache = tv["cache"]
            tgt = jnp.argmax(t_logits, axis=-1).astype(pair.dtype)
            match = (props == tgt[:, :G]).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)       # (B,)
            corr = jnp.take_along_axis(tgt, a[:, None], axis=1)
            cand = jnp.where(
                jnp.arange(G + 1)[None, :] < a[:, None],
                jnp.concatenate(
                    [props, jnp.zeros((B, 1), props.dtype)], axis=1
                ),
                corr,
            )  # (B, G+1)
            # --- commit: budget clamp + EOS cut + output scatter ----
            live = slot_req >= 0
            # acceptance accumulators: IN-BUDGET proposals only, the same
            # counting discipline as speculative.py's rate (a clamped
            # final round must not deflate it; self-draft reports 1.0)
            in_budget = jnp.where(live, jnp.minimum(G, slot_budget), 0)
            n_prop = n_prop + jnp.sum(in_budget)
            n_acc = n_acc + jnp.sum(jnp.minimum(a, in_budget))
            commit = jnp.where(
                live, jnp.minimum(a + 1, slot_budget), 0
            )
            if eos_id >= 0:
                is_eos = (cand == eos_id).astype(jnp.int32)
                # index of the first EOS in the candidate window (G+1 if
                # none): EOS is kept, everything after it is cut
                first_eos = jnp.sum(jnp.cumprod(1 - is_eos, axis=1),
                                    axis=1)
                hit = live & (first_eos < commit)
                commit = jnp.minimum(commit, first_eos + 1)
            else:
                hit = jnp.zeros((B,), bool)
            steps = jnp.arange(G + 1)[None, :]
            rows = jnp.where(
                live[:, None] & (steps < commit[:, None]),
                slot_req[:, None], N,
            )
            cols = jnp.minimum(out_n[:, None] + steps, cap - 1)
            out = out.at[rows, cols].set(cand.astype(out.dtype))
            out_n = out_n + commit
            slot_budget = jnp.where(hit, 0, slot_budget - commit)
            # rolling pair -> tokens at [L'-2, L'-1]: index commit maps
            # to L-2+commit in [pair | cand]
            allt = jnp.concatenate([pair, cand], axis=1)  # (B, G+3)
            pair = jnp.concatenate([
                jnp.take_along_axis(allt, commit[:, None], axis=1),
                jnp.take_along_axis(allt, commit[:, None] + 1, axis=1),
            ], axis=1)
            L = L + commit
            slot_req = jnp.where(slot_budget > 0, slot_req, -1)
            return (tcache, dcache, pair, L, pad, slot_req, slot_budget,
                    out, out_n, nxt, n_prop, n_acc)

        def body(state):
            slot_req, nxt = state[5], state[9]
            state = jax.lax.cond(
                jnp.any(slot_req < 0) & (nxt < N), admit_all,
                lambda s: s, state,
            )
            return spec_round(state)

        def cond(state):
            slot_budget, nxt = state[6], state[9]
            return (nxt < N) | jnp.any(slot_budget > 0)

        state = (
            tcache0,
            dcache0,
            jnp.zeros((B, 2), jnp.int32),    # rolling last-two tokens
            jnp.full((B,), 2, jnp.int32),    # L (>= 2: catch-up in bounds)
            jnp.zeros((B,), jnp.int32),      # pad
            jnp.full((B,), -1, jnp.int32),   # slot_req (-1 = free)
            jnp.zeros((B,), jnp.int32),      # slot_budget
            jnp.zeros((N + 1, cap), jnp.int32),  # out (+ dump row N)
            jnp.zeros((B,), jnp.int32),      # out_n
            jnp.int32(0),                    # next_req
            jnp.int32(0),                    # n_prop (in-budget proposals)
            jnp.int32(0),                    # n_acc (accepted of those)
        )
        state = jax.lax.while_loop(cond, body, state)
        return state[7][:N], state[10], state[11]

    return serve


def serve_fused_speculative(target_config: LlamaConfig, target_params,
                            draft_config: LlamaConfig, draft_params,
                            requests, max_new_tokens, *, gamma: int = 4,
                            max_batch: int = 8, prefill_width: int = 64,
                            eos_id: int | None = None):
    """One-dispatch continuous batching where every decode step is a
    speculative draft+verify round: the target model runs one
    (gamma+1)-window pass per ~(acceptance+1) committed tokens instead of
    one bandwidth-bound single-token step per token, and requests still
    join/leave the running batch at round boundaries.

    Greedy semantics: per-request outputs are BIT-IDENTICAL to solo
    ``generate()`` under the target (and so to ``serve_fused``) whatever
    the draft proposes — the acceptance rate only changes the speed.
    Same contract as :func:`serve_fused` otherwise (budgets per request
    or one int; optional ``eos_id`` keeps the EOS and frees the slot).

    The reference has no serving stack at all (SURVEY §2.2); this is the
    framework's own composition of its continuous batching and
    speculative decoding, fused for slow host<->device links.
    """
    if target_config.vocab_size != draft_config.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if max(target_config.decode_seq_shards,
           draft_config.decode_seq_shards) > 1:
        raise NotImplementedError(
            "fused speculative serving over the sequence-sharded cache: "
            "use one server per replica today"
        )
    target_config = target_config.with_resolved_decode_impl(target_params)
    draft_config = draft_config.with_resolved_decode_impl(draft_params)
    if isinstance(max_new_tokens, (int, np.integer)):
        budgets = [int(max_new_tokens)] * len(requests)
    else:
        budgets = [int(b) for b in max_new_tokens]
    eos = -1 if eos_id is None else int(eos_id)
    worst = max(budgets, default=0)
    # the verify window can scratch up to gamma slots past a lane's final
    # committed length — both caches must absorb it
    for name, cfg in (("target", target_config), ("draft", draft_config)):
        if prefill_width + worst + gamma > cfg.ctx_size:
            raise ValueError(
                f"{name}: prefill_width + max_new_tokens + gamma "
                f"({prefill_width}+{worst}+{gamma}) exceeds ctx_size "
                f"({cfg.ctx_size})"
            )
    _validate_workload(requests, budgets, prefill_width=prefill_width,
                       prefix_len=0, decode_chunk=1,
                       ctx_size=target_config.ctx_size)
    # the ONE host packer both fused servers share (_pack_workload): the
    # two schedulers must see identical workload layouts or they drift
    packed = _pack_workload(requests, budgets, prefill_width)
    if packed is None:
        return [[] for _ in requests]
    live, N, cap, prompts, lengths, budg = packed
    serve = _fused_spec_program(
        target_config, draft_config, max_batch, prefill_width, gamma, eos,
        cap, N,
    )
    tparams = (target_params if "params" in target_params
               else {"params": target_params})
    dparams = (draft_params if "params" in draft_params
               else {"params": draft_params})
    telem = obs.enabled()
    t0 = time.perf_counter() if telem else 0.0
    with obs.span("serving.fused_spec", requests=len(live), gamma=gamma):
        out, n_prop, n_acc = serve(
            tparams, dparams,
            jnp.asarray(prompts), jnp.asarray(lengths), jnp.asarray(budg),
        )
        out = np.asarray(out)  # the one blocking fetch
    results = _gather_results(out, live, len(requests))
    if telem:
        # counters ride the scalars the program already returns — the
        # extra fetch happens only with telemetry on
        obs.inc("spec_proposed_total", int(n_prop))
        obs.inc("spec_accepted_total", int(n_acc))
        _obs_fused_done(t0, results, live)
    return results
