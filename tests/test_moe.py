"""Mixture-of-Experts + expert parallelism oracles."""

import jax
import jax.numpy as jnp
import optax
import pytest

from ddl25spring_tpu.models import Llama, LlamaConfig
from ddl25spring_tpu.ops import causal_lm_loss
from ddl25spring_tpu.parallel import apply_shardings, llama_moe_ep_shardings, make_mesh

CFG = LlamaConfig(vocab_size=64, dmodel=32, nr_heads=2, nr_layers=2,
                  ctx_size=16, nr_experts=8, expert_topk=2)


@pytest.fixture(scope="module")
def setup():
    tokens = jax.random.randint(jax.random.key(0), (4, CFG.ctx_size), 0,
                                CFG.vocab_size)
    model = Llama(CFG)
    params = model.init(jax.random.key(1), tokens)
    return model, params, tokens


def test_moe_single_expert_equals_swiglu():
    """With E=1, k=1 the gate is exactly 1, so the layer's output must equal
    the plain SwiGLU computed by hand from its own params — an end-to-end
    check of the dense-dispatch einsums."""
    from ddl25spring_tpu.models.moe import MoEMLP
    import flax.linen as nn

    x = jax.random.normal(jax.random.key(2), (2, 8, CFG.dmodel))
    moe = MoEMLP(CFG, nr_experts=1, topk=1)
    p = moe.init(jax.random.key(3), x)
    out = moe.apply(p, x)
    w = p["params"]
    expected = (nn.silu(x @ w["w1"][0]) * (x @ w["w3"][0])) @ w["w2"][0]
    assert jnp.allclose(out, expected, atol=1e-5)


def test_moe_topk_sparsity_and_aux_load():
    """The layer's own sown router probs must be a distribution, the output
    must change only through the top-k experts, and moe_aux_load over the
    sown intermediates must hit its uniform-routing minimum (1.0) when the
    router is unbiased."""
    from ddl25spring_tpu.models.moe import MoEMLP, moe_aux_load

    x = jax.random.normal(jax.random.key(2), (2, 8, CFG.dmodel))
    moe = MoEMLP(CFG, nr_experts=8, topk=2)
    p = moe.init(jax.random.key(3), x)
    out, inter = moe.apply(p, x, mutable=["intermediates"])
    probs = inter["intermediates"]["router_probs"][0]
    assert probs.shape == (2, 8, 8)
    assert jnp.allclose(probs.sum(-1), 1.0, atol=1e-5)
    aux = moe_aux_load(inter)
    assert aux >= 1.0 - 1e-5  # E * sum(mean_e^2) is minimised at uniform

    # a zeroed router gives exactly uniform probs -> aux == 1
    p0 = jax.tree.map(lambda a: a, p)
    p0["params"]["router"]["kernel"] = jnp.zeros_like(
        p["params"]["router"]["kernel"]
    )
    _, inter0 = moe.apply(p0, x, mutable=["intermediates"])
    assert jnp.allclose(moe_aux_load(inter0), 1.0, atol=1e-5)

    # with topk=2, zeroing the two selected experts' outputs for a token must
    # zero that token's output: verify output is a combination of <=2 experts
    top_i = jax.lax.top_k(probs, 2)[1]
    w = dict(p["params"])
    out_full = moe.apply({"params": w}, x)
    # kill every expert NOT in token (0,0)'s top-2; its output must not move
    keep = set(int(e) for e in top_i[0, 0])
    w_kill = dict(w)
    for name in ("w1", "w2", "w3"):
        mask = jnp.array([1.0 if e in keep else 0.0 for e in range(8)])
        w_kill[name] = w[name] * mask.reshape(-1, 1, 1)
    out_kill = moe.apply({"params": w_kill}, x)
    assert jnp.allclose(out_kill[0, 0], out_full[0, 0], atol=1e-5)


def test_moe_topk_validation():
    from ddl25spring_tpu.models.moe import MoEMLP

    x = jnp.zeros((1, 4, CFG.dmodel))
    with pytest.raises(ValueError, match="expert_topk"):
        MoEMLP(CFG, nr_experts=1, topk=2).init(jax.random.key(0), x)


def test_moe_llama_trains(setup):
    model, params, tokens = setup
    opt = optax.adam(3e-3)

    @jax.jit
    def step(p, s, t):
        loss, g = jax.value_and_grad(
            lambda p: causal_lm_loss(model.apply(p, t), t)
        )(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    s = opt.init(params)
    p = params
    losses = []
    for _ in range(5):
        p, s, loss = step(p, s, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_ep_sharded_step_matches_replicated(setup):
    """Expert-sharded training step must equal the unsharded one — EP is a
    pure layout change."""
    model, params, tokens = setup
    opt = optax.sgd(0.1)

    def loss_fn(p, t):
        return causal_lm_loss(model.apply(p, t), t)

    l_ref, g_ref = jax.value_and_grad(loss_fn)(params, tokens)
    p_ref = optax.apply_updates(params, opt.update(g_ref, opt.init(params))[0])

    mesh = make_mesh({"expert": 8})
    shardings = llama_moe_ep_shardings(mesh, params)
    # stacked expert kernels must actually be expert-sharded, not replicated
    specs = jax.tree_util.tree_leaves_with_path(shardings)
    assert any("w1" in str(path) and s.spec != () and s.spec[0] == "expert"
               for path, s in specs)
    p_sh = apply_shardings(params, shardings)

    @jax.jit
    def step(p, s, t):
        loss, g = jax.value_and_grad(loss_fn)(p, t)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    p_ep, _, l_ep = step(p_sh, opt.init(p_sh), tokens)
    assert jnp.allclose(l_ep, l_ref, atol=1e-5)
    for a, b in zip(jax.tree.leaves(p_ep), jax.tree.leaves(p_ref)):
        assert jnp.allclose(a, b, atol=1e-4)


def test_run_lm_ep_strategy_converges():
    from ddl25spring_tpu.configs import LmConfig
    from ddl25spring_tpu.run_lm import run

    losses = run(LmConfig(strategy="ep", batch_size=8, seq_l=32, dmodel=32,
                          nr_heads=2, nr_layers=2, nr_iters=6, lr=3e-3),
                 log_every=5)
    assert losses[-1] < losses[0]
