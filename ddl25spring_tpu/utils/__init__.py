from .trees import (
    tree_stack,
    tree_unstack,
    tree_weighted_mean,
    tree_select,
    tree_add,
    tree_sub,
    tree_scale,
    tree_zeros_like,
    tree_vector,
    tree_l2_norm,
    tree_size,
)
from .rng import client_round_key, epoch_key, seed_key
from .metrics import RunResult
from .checkpoint import Checkpointer
from .logging import MetricsLogger, profile_trace, read_jsonl, timed

__all__ = [
    "Checkpointer",
    "MetricsLogger",
    "profile_trace",
    "read_jsonl",
    "timed",
    "tree_stack",
    "tree_unstack",
    "tree_weighted_mean",
    "tree_select",
    "tree_add",
    "tree_sub",
    "tree_scale",
    "tree_zeros_like",
    "tree_vector",
    "tree_l2_norm",
    "tree_size",
    "client_round_key",
    "epoch_key",
    "seed_key",
    "RunResult",
]
