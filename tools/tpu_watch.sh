#!/bin/bash
# Probe the remote TPU tunnel every ~100s; append status lines to
# /tmp/tpu_status.log.  Used while building to know the moment the tunnel
# comes back so benches can run immediately.
while true; do
  ts=$(date +%H:%M:%S)
  if timeout 60 python - <<'EOF' >/dev/null 2>&1
import numpy as np, jax.numpy as jnp
np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
EOF
  then
    echo "$ts UP" >> /tmp/tpu_status.log
  else
    echo "$ts down" >> /tmp/tpu_status.log
  fi
  sleep 100
done
