"""Seeded, deterministic fault injection from a compact spec string.

The reference course never simulates failure at all (SURVEY.md §5); the
byzantine benches inject *adversarial* updates but every round, request,
and process still completes.  A :class:`FaultPlan` is the missing piece:
one object that injects the *operational* failure modes — client dropout,
straggler delay, corrupted (non-finite) updates, serving-request stalls,
and host crash points — **reproducibly**, so every fault a test or bench
observes can be replayed bit-for-bit.

Spec grammar (comma-separated ``key=value`` tokens)::

    drop=0.2              per-round client dropout probability
    nan=0.05              per-client probability of an all-NaN update
    inf=0.05              per-client probability of an all-Inf update
    straggle=0.3:2.0      straggler probability : mean delay seconds
                          (per-client delay ~ U[0, 2*mean])
    serve_timeout=0.1     per-request probability a serving request stalls
                          past its deadline
    crash=5               raise InjectedCrash at training round 5
    kill=5                hard-exit the process at round 5 (os._exit —
                          simulates SIGKILL/OOM for crash-recovery tests)
    seed=42               fault randomness seed (default 0)

e.g. ``FaultPlan.parse("drop=0.2,nan=0.05,seed=7")``.

Determinism contract: FL-round masks are derived inside the jitted round
from ``fold_in(PRNGKey(seed), round_idx)`` — a pure function of
``(seed, round)`` that works identically under a tracer (bench.py's
fused ``fori_loop``) and eagerly (tests replicating a draw).  Host-side
faults (serving, crash points) hash stable request/round identifiers
with crc32, so they reproduce across processes (unlike ``hash()``,
which is salted per interpreter).
"""

from __future__ import annotations

import dataclasses
import os
import zlib
from dataclasses import dataclass

import numpy as np

from .. import obs


class InjectedCrash(RuntimeError):
    """Raised by ``FaultPlan.maybe_crash`` at a ``crash=N`` point — an
    exception-shaped process death (stack unwinds; ``kill=N`` is the
    no-cleanup variant)."""


_FLOAT_KEYS = ("drop", "nan", "inf", "serve_timeout")
# domain-separation tags for the per-kind fault key streams (arbitrary
# distinct constants; folded on top of the round key)
_TAG_DROP, _TAG_NAN, _TAG_INF, _TAG_STRAGGLE = 0xD0, 0xA1, 0x1F, 0x57


@dataclass(frozen=True)
class FaultPlan:
    seed: int = 0
    drop: float = 0.0           # client dropout probability per round
    nan: float = 0.0            # per-client all-NaN update probability
    inf: float = 0.0            # per-client all-Inf update probability
    straggle: float = 0.0       # straggler probability per client
    straggle_s: float = 0.0     # mean injected delay (delay ~ U[0, 2*mean])
    serve_timeout: float = 0.0  # serving-request stall probability
    crash: int | None = None    # raise InjectedCrash at this round
    kill: int | None = None     # os._exit at this round (SIGKILL-like)

    # -- construction ----------------------------------------------------

    @classmethod
    def parse(cls, spec: str | None) -> "FaultPlan | None":
        """``None``/empty spec -> ``None`` (no plan; callers keep the
        exact fault-free code path)."""
        if not spec:
            return None
        kw: dict = {}
        for token in spec.split(","):
            token = token.strip()
            if not token:
                continue
            key, sep, value = token.partition("=")
            key = key.strip()
            value = value.strip()
            if not sep or not value:
                raise ValueError(
                    f"fault spec token {token!r} is not key=value "
                    f"(full spec: {spec!r})"
                )
            try:
                if key in _FLOAT_KEYS:
                    kw[key] = float(value)
                elif key == "straggle":
                    prob, _, delay = value.partition(":")
                    kw["straggle"] = float(prob)
                    kw["straggle_s"] = float(delay) if delay else 1.0
                elif key in ("crash", "kill", "seed"):
                    kw[key] = int(value)
                else:
                    raise KeyError(key)
            except KeyError:
                raise ValueError(
                    f"unknown fault kind {key!r} in spec {spec!r}; known: "
                    f"{', '.join(_FLOAT_KEYS)}, straggle, crash, kill, seed"
                ) from None
            except ValueError as e:
                raise ValueError(
                    f"bad value for {key!r} in fault spec {spec!r}: {e}"
                ) from None
        plan = cls(**kw)
        plan.validate()
        return plan

    def validate(self) -> None:
        for key in _FLOAT_KEYS + ("straggle",):
            v = getattr(self, key)
            if not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"{key}={v} outside [0, 1] — fault rates are "
                    "probabilities"
                )
        if self.straggle_s < 0:
            raise ValueError(f"straggle_s={self.straggle_s} must be >= 0")

    def describe(self) -> str:
        """Round-trippable compact spec of the non-default fields."""
        parts = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v == f.default or f.name == "straggle_s":
                continue
            if f.name == "straggle":
                parts.append(f"straggle={v}:{self.straggle_s}")
            else:
                parts.append(f"{f.name}={v}")
        return ",".join(parts)

    # -- what the plan can do --------------------------------------------

    @property
    def corrupts(self) -> bool:
        return self.nan > 0 or self.inf > 0

    @property
    def drops(self) -> bool:
        return self.drop > 0

    @property
    def straggles(self) -> bool:
        return self.straggle > 0 and self.straggle_s > 0

    @property
    def affects_fl_round(self) -> bool:
        return self.corrupts or self.drops or self.straggles

    # -- FL-round masks (jit-traceable) ----------------------------------

    def round_masks(self, round_idx, nr: int, deadline_s: float | None = None):
        """Per-client fault draws for one round: ``(keep, nan_mask,
        inf_mask, late)``, each a ``(nr,)`` bool array.

        Pure function of ``(seed, round_idx)`` via fold_in, so it traces
        under jit (``round_idx`` may be a tracer) AND replays eagerly —
        the engine derives the masks inside the compiled round while
        tests re-derive the identical masks host-side.  ``late`` marks
        stragglers whose drawn delay exceeds ``deadline_s`` (all-False
        without a deadline: a synchronous round just waits)."""
        import jax
        import jax.numpy as jnp

        key = jax.random.fold_in(
            jax.random.PRNGKey(self.seed), round_idx
        )

        def draw(tag, prob):
            if prob <= 0.0:
                return jnp.zeros((nr,), bool)
            u = jax.random.uniform(jax.random.fold_in(key, tag), (nr,))
            return u < prob

        keep = ~draw(_TAG_DROP, self.drop)
        nan_mask = draw(_TAG_NAN, self.nan)
        inf_mask = draw(_TAG_INF, self.inf)
        late = jnp.zeros((nr,), bool)
        if self.straggles and deadline_s is not None:
            straggler = draw(_TAG_STRAGGLE, self.straggle)
            delay = (2.0 * self.straggle_s) * jax.random.uniform(
                jax.random.fold_in(key, _TAG_STRAGGLE + 1), (nr,)
            )
            late = straggler & (delay > deadline_s)
        return keep, nan_mask, inf_mask, late

    # -- host-side faults -------------------------------------------------

    def serving_fault(self, rid) -> bool:
        """Deterministic per-request stall draw (keyed on a stable crc32
        of the request id, so it reproduces across processes)."""
        if self.serve_timeout <= 0:
            return False
        h = zlib.crc32(repr(rid).encode()) ^ (self.seed * 0x9E3779B1)
        u = (h & 0xFFFFFFFF) / 2.0 ** 32
        hit = u < self.serve_timeout
        if hit:
            obs.inc("resilience_faults_injected_total", kind="serve_timeout")
        return hit

    def maybe_crash(self, step: int) -> None:
        """Fire the configured crash point for ``step`` (no-op
        otherwise).  ``crash``: raise :class:`InjectedCrash` (stack
        unwinds, finally-blocks run).  ``kill``: ``os._exit(23)`` — the
        SIGKILL/OOM simulation crash-recovery tests need, since nothing
        (not even orbax's atomic-commit finalizers) runs after it."""
        if self.kill is not None and step == self.kill:
            obs.inc("resilience_faults_injected_total", kind="kill")
            os._exit(23)
        if self.crash is not None and step == self.crash:
            obs.inc("resilience_faults_injected_total", kind="crash")
            raise InjectedCrash(
                f"injected crash at step {step} (fault plan "
                f"{self.describe() or 'crash'!r})"
            )
