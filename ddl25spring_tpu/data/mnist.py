"""MNIST loader with a deterministic synthetic fallback.

The reference downloads MNIST via torchvision and normalizes by the canonical
train mean/std 0.1307 / 0.3081 (hfl_complete.py:19-31).  This environment has
no network egress, so:

1. if real MNIST is available (``$DDL25_DATA_DIR/mnist.npz``, a torchvision
   ``MNIST/raw`` directory, or an npz in ``~/.cache/ddl25spring``), use it;
2. otherwise generate **synthetic MNIST**: 10 smooth class-prototype images
   with per-sample random shifts and pixel noise.  It has the same shapes,
   label structure and normalization as MNIST, is deterministic given the
   seed, and is learnable by the same CNN — so every pipeline and test runs
   unchanged; only absolute accuracy numbers differ from the homework tables.
"""

from __future__ import annotations

import gzip
import os
import struct
import sys
from dataclasses import dataclass
from pathlib import Path

import numpy as np

MNIST_MEAN = 0.1307
MNIST_STD = 0.3081

_announced: set[str] = set()


class DatasetNotFound(FileNotFoundError):
    """Raised by loaders with ``synthetic_fallback=False`` when the dataset is
    absent from every candidate root.  A dedicated type so callers opting into
    their own fallback don't also swallow a *partial/corrupt* real dataset's
    ``FileNotFoundError`` (e.g. an interrupted copy missing one CIFAR batch),
    which should stay loud."""


def announce_synthetic_fallback(dataset: str) -> None:
    """Loud once-per-process stderr banner when a run falls back to the
    synthetic dataset, so no CLI/benchmark result can be mistaken for a
    real-data number (absolute accuracies won't match the homework tables)."""
    if dataset in _announced:
        return
    _announced.add(dataset)
    print(
        f"[ddl25spring_tpu] SYNTHETIC-DATA FALLBACK: real {dataset} not "
        f"found (set DDL25_DATA_DIR to point at it) — results are "
        f"deterministic but NOT comparable to real-data tables",
        file=sys.stderr, flush=True,
    )


@dataclass
class ImageDataset:
    train_x: np.ndarray  # (n_train, H, W, C) float32 normalized, or uint8 raw
    train_y: np.ndarray  # (n_train,) int32
    test_x: np.ndarray
    test_y: np.ndarray
    synthetic: bool


def raw_dataset(train_x, train_y, test_x, test_y, synthetic: bool) -> ImageDataset:
    """Package UN-normalized uint8 images (channel axis added if missing).

    The raw representation is 4x smaller than normalized float32 — on a
    remote-tunnel TPU the host->device copy of a 256-client CIFAR stack is
    ~630 MB as f32 vs ~157 MB as uint8, minutes of bench startup.  Pair with
    an on-device ``input_transform`` (fl.task.classification_task) that
    normalizes per batch; XLA fuses the cast+scale into the first conv."""
    def chan(x):
        x = np.ascontiguousarray(x, dtype=np.uint8)
        return x[..., None] if x.ndim == 3 else x

    return ImageDataset(
        train_x=chan(train_x), train_y=np.asarray(train_y, np.int32),
        test_x=chan(test_x), test_y=np.asarray(test_y, np.int32),
        synthetic=synthetic,
    )


def make_input_transform(mean, std, dtype=None):
    """On-device normalizer factory for raw uint8 batches:
    ``f(x_uint8) -> (x/255 - mean)/std`` computed in ``dtype`` (default f32).
    Runs inside jitted loss/score fns; see :func:`raw_dataset` for why raw
    uint8 + device-side normalize."""
    import jax.numpy as jnp

    dt = dtype or jnp.float32
    mean = jnp.asarray(mean, dt)
    inv_std = jnp.asarray(1.0 / np.asarray(std, np.float32), dt)

    def transform(x):
        return (x.astype(dt) / 255.0 - mean) * inv_std

    return transform


def mnist_input_transform(dtype=None):
    """Normalizer for ``load_mnist(raw=True)`` (canonical torchvision
    mean/std, hfl_complete.py:19-31)."""
    return make_input_transform(MNIST_MEAN, MNIST_STD, dtype)


def candidate_data_dirs():
    """Data-root search order shared by all dataset loaders."""
    env = os.environ.get("DDL25_DATA_DIR")
    if env:
        yield Path(env)
    yield Path.home() / ".cache" / "ddl25spring"
    yield Path("/root/data")


_candidate_dirs = candidate_data_dirs


def _read_idx_images(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic in {path}"
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic in {path}"
        return np.frombuffer(f.read(), dtype=np.uint8)


def _try_load_real(raw: bool = False) -> ImageDataset | None:
    def package(tx, ty, ex, ey):
        if raw:
            return raw_dataset(tx, ty, ex, ey, synthetic=False)
        return _normalize(tx, ty, ex, ey, synthetic=False)

    for root in _candidate_dirs():
        npz = root / "mnist.npz"
        if npz.exists():
            d = np.load(npz)
            return package(d["train_x"], d["train_y"], d["test_x"], d["test_y"])
        # NB: do not name this loop variable `raw` — it would shadow the
        # raw= parameter that the `package` closure reads
        for idx_dir in (root / "MNIST" / "raw", root / "mnist"):
            stems = {
                "train_x": "train-images-idx3-ubyte",
                "train_y": "train-labels-idx1-ubyte",
                "test_x": "t10k-images-idx3-ubyte",
                "test_y": "t10k-labels-idx1-ubyte",
            }
            found = {}
            for key, stem in stems.items():
                for suffix in ("", ".gz"):
                    p = idx_dir / (stem + suffix)
                    if p.exists():
                        found[key] = p
                        break
            if len(found) == 4:
                return package(
                    _read_idx_images(found["train_x"]),
                    _read_idx_labels(found["train_y"]),
                    _read_idx_images(found["test_x"]),
                    _read_idx_labels(found["test_y"]),
                )
    return None


def _normalize(
    train_x, train_y, test_x, test_y, synthetic: bool,
    mean=MNIST_MEAN, std=MNIST_STD,
) -> ImageDataset:
    def norm(x):
        x = x.astype(np.float32) / 255.0
        x = (x - mean) / std
        if x.ndim == 3:
            x = x[..., None]
        return x

    return ImageDataset(
        train_x=norm(train_x),
        train_y=train_y.astype(np.int32),
        test_x=norm(test_x),
        test_y=test_y.astype(np.int32),
        synthetic=synthetic,
    )


def _smooth_field(rng: np.random.Generator, size: int) -> np.ndarray:
    """Low-frequency random image in [0, 1]: random coarse grid, upsampled."""
    coarse = rng.random((7, 7))
    grid = np.minimum(np.arange(size) * 7 // size, 6)
    fine = coarse[np.ix_(grid, grid)]
    # simple box blur for smoothness
    k = 3
    padded = np.pad(fine, k, mode="edge")
    out = np.zeros_like(fine)
    for dy in range(-k, k + 1):
        for dx in range(-k, k + 1):
            out += padded[
                k + dy : k + dy + size, k + dx : k + dx + size
            ]
    out /= (2 * k + 1) ** 2
    out -= out.min()
    out /= max(out.max(), 1e-8)
    return out


def synthetic_image_dataset(
    n_train: int = 60000,
    n_test: int = 10000,
    size: int = 28,
    nr_classes: int = 10,
    channels: int = 1,
    noise: float = 0.25,
    max_shift: int = 3,
    seed: int = 0,
    mean=MNIST_MEAN,
    std=MNIST_STD,
    raw: bool = False,
) -> ImageDataset:
    """Deterministic MNIST-shaped classification dataset (see module docstring)."""
    rng = np.random.default_rng(seed)
    protos = np.stack(
        [
            np.stack([_smooth_field(rng, size) for _ in range(channels)], axis=-1)
            for _ in range(nr_classes)
        ]
    )  # (classes, size, size, channels)

    def make(n, rng):
        y = rng.integers(0, nr_classes, size=n).astype(np.int32)
        x = protos[y]  # (n, size, size, channels)
        shifts = rng.integers(-max_shift, max_shift + 1, size=(n, 2))
        # roll each image by its shift (vectorized via gather on index grids)
        idx = np.arange(size)
        rows = (idx[None, :] - shifts[:, 0:1]) % size  # (n, size)
        cols = (idx[None, :] - shifts[:, 1:2]) % size
        x = x[np.arange(n)[:, None, None], rows[:, :, None], cols[:, None, :]]
        x = x + noise * rng.standard_normal(x.shape)
        x = np.clip(x, 0.0, 1.0)
        return (255 * x).astype(np.uint8), y

    train_x, train_y = make(n_train, rng)
    test_x, test_y = make(n_test, rng)
    if raw:
        return raw_dataset(train_x, train_y, test_x, test_y, synthetic=True)
    ds = _normalize(train_x.squeeze(-1) if channels == 1 else train_x,
                    train_y, test_x.squeeze(-1) if channels == 1 else test_x,
                    test_y, synthetic=True, mean=mean, std=std)
    return ds


def load_mnist(
    synthetic_fallback: bool = True,
    n_train: int = 60000,
    n_test: int = 10000,
    seed: int = 0,
    raw: bool = False,
) -> ImageDataset:
    """``raw=True`` returns uint8 images (same pixels/rng stream as the
    normalized dataset); normalize on device with
    :func:`mnist_input_transform`."""
    real = _try_load_real(raw=raw)
    if real is not None:
        return real
    if not synthetic_fallback:
        raise DatasetNotFound(
            "MNIST not found on disk and synthetic fallback disabled; "
            "set DDL25_DATA_DIR to a directory containing mnist.npz or MNIST/raw"
        )
    announce_synthetic_fallback("mnist")
    return synthetic_image_dataset(n_train=n_train, n_test=n_test, seed=seed,
                                   raw=raw)
