"""Trace context: deterministic trace/span ids + cross-process propagation.

Stdlib-only (the ``tests/test_obs.py`` jax-import-free guard covers this
module).  Every span recorded by :mod:`ddl25spring_tpu.obs.core` carries a
``trace_id`` / ``span_id`` / ``parent_id`` triple threaded through a
process-wide thread-local span stack kept here, so span JSONL from the FL
server, its spawned client/eval subprocesses, multihost ranks and
autoresume restarts can be joined into ONE timeline by
``obs/export.py``.

Id scheme (all lowercase hex, W3C trace-context sized):

* ``trace_id``  — 32 hex chars.  ``start(seed=...)`` derives it
  deterministically from the seed via blake2b; unseeded traces mix wall
  time, pid and entropy.
* ``span_id``   — 16 hex chars,
  ``blake2b(f"{trace_id}:{lineage}:{process}:{seq}")`` with a per-process
  monotonic ``seq`` and a spawn-lineage tag inherited from the parent
  process (``DDL25_TRACE_CHILD``) — deterministic given the trace id, the
  spawn topology and the span order, yet collision-free across processes
  that share a rank.

Propagation uses a ``traceparent``-style string
``00-<trace_id>-<span_id>-01`` carried in the ``DDL25_TRACEPARENT``
environment variable: a parent process calls :func:`child_env` when
spawning (the innermost active span on the calling thread becomes the
remote parent), and the child adopts it lazily the first time a span is
opened — nothing to configure on the child side.  Multihost ranks tag
every span with their ``process_index`` (:func:`set_process_index`, wired
from ``parallel/multihost.py``); autoresume persists the root traceparent
next to its checkpoints so a resumed run continues the same trace.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
import time

TRACEPARENT_ENV = "DDL25_TRACEPARENT"
# Spawn lineage tag ("<parent span id>/<spawn #>", chained): hashed into
# every span id so two processes that share a trace_id, a process_index
# and a span sequence number (e.g. rank-0 server and the client subprocess
# it spawns) can never mint colliding ids.
CHILD_TAG_ENV = "DDL25_TRACE_CHILD"

# Anchor mapping perf_counter readings onto the wall clock, taken ONCE per
# process: span start/end timestamps derived from it are mutually
# consistent to perf_counter precision (time.time() per span would not be),
# which is what keeps exported timelines properly nested.
EPOCH0 = time.time() - time.perf_counter()

_lock = threading.Lock()
_tls = threading.local()
_seq = itertools.count()

_trace_id: str | None = None
_root_parent: str | None = None  # remote parent span for this process's roots
_process: int | None = None
_spawn_seq = itertools.count()


def _child_tag() -> str:
    return os.environ.get(CHILD_TAG_ENV, "")


def _hash_hex(material: str, nbytes: int) -> str:
    return hashlib.blake2b(material.encode(), digest_size=nbytes).hexdigest()


def _is_hex(s: str, n: int) -> bool:
    if len(s) != n:
        return False
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: str) -> tuple[str, str] | None:
    """``(trace_id, span_id)`` from a traceparent string, or None."""
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _ver, tid, sid, _flags = parts
    if not (_is_hex(tid, 32) and _is_hex(sid, 16)):
        return None
    if set(tid) == {"0"} or set(sid) == {"0"}:
        return None
    return tid, sid


# -- process identity ----------------------------------------------------


def set_process_index(index: int):
    """Tag every subsequent span with this rank (multihost wires it from
    ``jax.process_index()`` at distributed init)."""
    global _process
    _process = int(index)


def process_index() -> int:
    if _process is not None:
        return _process
    env = os.environ.get("JAX_PROCESS_ID", "")
    try:
        return int(env)
    except ValueError:
        return 0


# -- trace lifecycle -----------------------------------------------------


def start(seed=None) -> str:
    """Start a NEW trace (ignoring any inherited traceparent) and return
    its trace_id.  ``seed`` makes the id — and through it every span id —
    deterministic across runs."""
    global _trace_id, _root_parent
    if seed is None:
        material = f"{time.time_ns()}:{os.getpid()}:{os.urandom(8).hex()}"
    else:
        material = f"ddl25spring:{seed}"
    with _lock:
        _trace_id = _hash_hex("trace:" + material, 16)
        _root_parent = None
    return _trace_id


def adopt(traceparent: str) -> bool:
    """Join the trace described by ``traceparent``: subsequent root spans
    in this process parent under its span_id.  Returns False (and changes
    nothing) when the string does not parse."""
    global _trace_id, _root_parent
    parsed = parse_traceparent(traceparent)
    if parsed is None:
        return False
    with _lock:
        _trace_id, _root_parent = parsed
    return True


def ensure() -> str:
    """The current trace_id, lazily initialised: adopt ``DDL25_TRACEPARENT``
    from the environment if present, else start a fresh trace."""
    if _trace_id is not None:
        return _trace_id
    with _lock:
        if _trace_id is not None:
            return _trace_id
    env = os.environ.get(TRACEPARENT_ENV)
    if env and adopt(env):
        return _trace_id
    return start()


def trace_id() -> str | None:
    """The active trace_id WITHOUT forcing one to exist."""
    return _trace_id


def reset():
    """Forget all trace state (fresh trace on next span) — tests and
    deliberate run boundaries only."""
    global _trace_id, _root_parent, _process, _seq, _spawn_seq
    with _lock:
        _trace_id = None
        _root_parent = None
        _process = None
        _seq = itertools.count()
        _spawn_seq = itertools.count()
    os.environ.pop(TRACEPARENT_ENV, None)
    os.environ.pop(CHILD_TAG_ENV, None)


# -- span stack ----------------------------------------------------------


def _stack() -> list:
    s = getattr(_tls, "spans", None)
    if s is None:
        s = _tls.spans = []
    return s


def new_span_id() -> str:
    material = (f"{ensure()}:{_child_tag()}:{process_index()}"
                f":{next(_seq)}")
    return _hash_hex(material, 8)


def begin_span(name: str):
    """Push a span; returns ``(trace_id, span_id, parent_id, parent_name)``
    — parent ids come from the innermost open span on this thread, else
    from the adopted remote parent (None for a true root)."""
    tid = ensure()
    sid = new_span_id()
    stack = _stack()
    if stack:
        parent_name, parent_id = stack[-1]
    else:
        parent_name, parent_id = None, _root_parent
    stack.append((name, sid))
    return tid, sid, parent_id, parent_name


def end_span() -> int:
    """Pop the innermost span; returns the remaining depth."""
    stack = _stack()
    if stack:
        stack.pop()
    return len(stack)


def current_span_id() -> str | None:
    stack = _stack()
    return stack[-1][1] if stack else None


# -- propagation ---------------------------------------------------------


def traceparent() -> str:
    """Traceparent for handing to a child process: the innermost active
    span on this thread, else the adopted remote parent, else a synthetic
    process-root id (deterministic from the trace id)."""
    tid = ensure()
    sid = current_span_id() or _root_parent
    if sid is None:
        sid = _hash_hex(f"{tid}:root", 8)
    return format_traceparent(tid, sid)


def child_env(base=None) -> dict:
    """A copy of ``base`` (default ``os.environ``) with the current
    traceparent and a unique spawn-lineage tag injected — pass as
    ``env=`` when spawning subprocesses."""
    env = dict(os.environ if base is None else base)
    tp = traceparent()
    env[TRACEPARENT_ENV] = tp
    env[CHILD_TAG_ENV] = f"{tp.split('-')[2]}/{next(_spawn_seq)}"
    return env
