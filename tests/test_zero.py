"""ZeRO weight-update sharding oracle: element-identical to unsharded DP.

For elementwise optimizers the sharded update computes exactly the same
numbers as the replicated one, so the test demands near-bitwise agreement
with make_dp_train_step across steps — the same equivalence style as
DP ≡ single-device (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import optax
import pytest

from ddl25spring_tpu.models import MnistCnn
from ddl25spring_tpu.ops import nll_loss
from ddl25spring_tpu.parallel import (
    make_dp_train_step,
    make_mesh,
    make_zero_dp_train_step,
    make_zero_server_step,
)


@pytest.fixture(scope="module")
def problem():
    model = MnistCnn()
    x = jax.random.normal(jax.random.key(0), (16, 28, 28, 1))
    y = jax.random.randint(jax.random.key(1), (16,), 0, 10)

    def loss_fn(params, batch):
        xb, yb = batch
        out = model.apply(params, xb, train=False)
        return nll_loss(out, yb, jnp.ones_like(yb, bool))

    params = model.init(jax.random.key(2), x[:1])
    return loss_fn, params, (x, y)


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_zero_dp_matches_plain_dp(problem, opt_name):
    loss_fn, params, batch = problem
    opt = {"sgd": lambda: optax.sgd(0.05),
           "adam": lambda: optax.adam(1e-3)}[opt_name]()
    mesh = make_mesh({"data": 8})

    plain = make_dp_train_step(loss_fn, opt, mesh)
    zero, z_state = make_zero_dp_train_step(loss_fn, opt, mesh, params)

    p_a, s_a = params, opt.init(params)
    p_b = params
    for _ in range(5):
        p_a, s_a, l_a = plain(p_a, s_a, batch)
        p_b, z_state, l_b = zero(p_b, z_state, batch)
    assert jnp.allclose(l_a, l_b, atol=1e-6)
    for a, b in zip(jax.tree.leaves(p_a), jax.tree.leaves(p_b)):
        assert jnp.allclose(a, b, atol=1e-5), "params diverged"


def test_zero_opt_state_is_sharded(problem):
    """The point of ZeRO: every device holds 1/W of each Adam moment, not a
    replica — the state leaves must carry the (W, chunk) shard layout."""
    loss_fn, params, batch = problem
    mesh = make_mesh({"data": 8})
    opt = optax.adam(1e-3)
    _, z_state = make_zero_dp_train_step(loss_fn, opt, mesh, params)

    total = sum(p.size for p in jax.tree.leaves(params))
    chunk = -(-total // 8)
    arrays = [l for l in jax.tree.leaves(z_state)
              if hasattr(l, "ndim") and l.ndim > 0]
    assert arrays, "expected sharded moment arrays"
    for leaf in arrays:
        assert leaf.shape == (8, chunk)
        spec = leaf.sharding.spec
        assert spec and spec[0] == "data"


def test_zero_rejects_non_elementwise_optimizer(problem):
    """Global-norm clipping mixes coordinates, so ZeRO sharding would
    silently change the dynamics — the factory must refuse it."""
    loss_fn, params, _ = problem
    mesh = make_mesh({"data": 8})
    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3))
    with pytest.raises(ValueError, match="elementwise"):
        make_zero_dp_train_step(loss_fn, opt, mesh, params)


@pytest.mark.parametrize("opt_name", ["sgd", "avgm", "adam", "yogi"])
@pytest.mark.parametrize("world", [1, 2, 8])
def test_zero_server_step_matches_replicated(world, opt_name):
    """The federated variant (FedOpt's pseudo-gradient update on a 1/W
    parameter slice per replica) must track the replicated server
    optimizer element for element across steps — same oracle discipline
    as the DP test above, over the FedOptServer optimizer family."""
    opt = {"sgd": lambda: optax.sgd(0.5),
           "avgm": lambda: optax.sgd(0.5, momentum=0.9),
           "adam": lambda: optax.adam(1e-2, eps=1e-3),
           "yogi": lambda: optax.yogi(1e-2, eps=1e-3)}[opt_name]()
    mesh = make_mesh({"clients": world},
                     devices=jax.devices()[:world])
    key = jax.random.key(7)
    params = {"w": jax.random.normal(key, (7, 5)),
              "b": jnp.zeros((5,))}
    step, z_state = make_zero_server_step(opt, mesh, params,
                                          axis="clients")
    r_state = opt.init(params)

    @jax.jit
    def replicated(params, opt_state, w_avg):
        delta = jax.tree.map(jnp.subtract, params, w_avg)
        updates, opt_state = opt.update(delta, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    p_z = p_r = params
    for t in range(4):
        w_avg = jax.tree.map(
            lambda p: p + 0.1 * jax.random.normal(
                jax.random.fold_in(key, t), p.shape),
            p_r,
        )
        p_z, z_state = step(p_z, z_state, w_avg)
        p_r, r_state = replicated(p_r, r_state, w_avg)
    for a, b in zip(jax.tree.leaves(p_z), jax.tree.leaves(p_r)):
        assert jnp.allclose(a, b, atol=1e-6), "server params diverged"


def test_zero_server_state_is_sharded():
    mesh = make_mesh({"clients": 4}, devices=jax.devices()[:4])
    params = {"w": jnp.zeros((7, 5)), "b": jnp.zeros((5,))}
    _, state = make_zero_server_step(optax.adam(1e-2), mesh, params,
                                     axis="clients")
    total = sum(p.size for p in jax.tree.leaves(params))
    chunk = -(-total // 4)
    arrays = [l for l in jax.tree.leaves(state)
              if hasattr(l, "ndim") and l.ndim > 0]
    assert arrays, "expected sharded moment arrays"
    for leaf in arrays:
        assert leaf.shape == (4, chunk)
        spec = leaf.sharding.spec
        assert spec and spec[0] == "clients"


def test_zero_server_rejects_non_elementwise_optimizer():
    mesh = make_mesh({"clients": 4}, devices=jax.devices()[:4])
    params = {"w": jnp.zeros((7, 5))}
    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-2))
    with pytest.raises(ValueError, match="elementwise"):
        make_zero_server_step(opt, mesh, params, axis="clients")


def test_zero_trains(problem):
    loss_fn, params, batch = problem
    mesh = make_mesh({"data": 8})
    opt = optax.adam(3e-3)
    zero, z_state = make_zero_dp_train_step(loss_fn, opt, mesh, params)
    losses = []
    p = params
    for _ in range(8):
        p, z_state, loss = zero(p, z_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
