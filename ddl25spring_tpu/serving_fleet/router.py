"""Host-side fleet router over N ``ContinuousBatcher`` replicas.

The router owns request placement only; each replica keeps its own
queue, pool, admission control and compiled programs (which the
``_programs`` lru shares across same-shape replicas — N replicas compile
ONCE).  Placement is prefix-affinity + least-load + SLO-slack
(``serving_fleet.policy``); a replica that still rejects
(:class:`~ddl25spring_tpu.models.serving.AdmissionRejected` — queue
full, SLO, pool) triggers a bounded re-route to the next-ranked replica
through :func:`~ddl25spring_tpu.resilience.retry.retry_call`, reusing
the rejection's ``reason``/``retry_after_s`` for telemetry and for the
error the caller finally sees (the rejection with the SOONEST
``retry_after_s`` across the fleet).

Autoscaling signals ride on ``obs``: per-replica queue-wait and
measured page-drain-rate gauges (``fleet_replica_queue_wait_s``,
``fleet_replica_drain_pps``) plus routing counters — these are the
inputs a scaler needs to decide "add a replica" (queue wait growing
fleet-wide) vs "rebalance" (one replica hot).

Like ``policy``, this module never imports jax: rejections are matched
structurally (``reason``/``retry_after_s`` attributes) so the router —
and its tests — run with fake replicas in a jax-free process.
"""

from __future__ import annotations

import time

from .. import obs
from ..resilience.retry import RetryError, retry_call
from . import policy

__all__ = ["FleetRouter"]


class _Rerouted(RuntimeError):
    """Internal: one replica rejected; carries the original exception so
    the retry loop can re-raise the real rejection when every candidate
    is exhausted (keeping the router import-independent of serving)."""

    def __init__(self, original):
        super().__init__(str(original))
        self.original = original


def _is_rejection(e: BaseException) -> bool:
    return hasattr(e, "reason") and hasattr(e, "retry_after_s")


class _FleetPoolView:
    """Duck-typed pool facade so :func:`loadgen.replay` can read fleet
    page residency: the peak is summed per replica (each pool peaks
    independently — the sum is the fleet's resident-KV high-water
    bound)."""

    def __init__(self, replicas):
        self._replicas = replicas

    @property
    def pages_peak(self) -> int:
        return sum(r._pool.pages_peak for r in self._replicas
                   if getattr(r, "_pool", None) is not None)

    @property
    def pages_in_use(self) -> int:
        return sum(r._pool.pages_in_use for r in self._replicas
                   if getattr(r, "_pool", None) is not None)


class FleetRouter:
    """Route requests over ``replicas`` (each a ``ContinuousBatcher`` —
    or anything with its submit/step/in_flight surface).

    ``max_reroutes`` bounds how many ADDITIONAL replicas a rejected
    request may try (default: all of them).  ``affinity_window`` is the
    prompt-head length used for the router's recency affinity map —
    requests sharing a head route to the replica that last served one,
    where its KV pages are warmest.  Exposes the same
    ``submit``/``step``/``drain``/``in_flight`` surface as a single
    batcher, so ``loadgen.replay`` and ``saturation_sweep`` drive a
    fleet unchanged.
    """

    def __init__(self, replicas, *, max_reroutes: int | None = None,
                 affinity_window: int = 16):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        if max_reroutes is not None and max_reroutes < 0:
            raise ValueError(
                f"max_reroutes must be >= 0, got {max_reroutes}")
        self.replicas = replicas
        self.max_reroutes = (len(replicas) - 1 if max_reroutes is None
                             else max_reroutes)
        self.affinity_window = affinity_window
        self._affinity: dict = {}   # prompt head -> last replica index
        self._owner: dict = {}      # in-flight rid -> replica index
        self.routing_trace: list = []  # (rid, replica index), append-only
        self.stats = {"routed": 0, "rerouted": 0, "rejected": 0,
                      "rerouted_by_reason": {}}

    # -- loadgen duck-type surface (drive a fleet like one batcher) ------

    @property
    def max_batch(self) -> int:
        return max(r.max_batch for r in self.replicas)

    @property
    def _paged(self) -> bool:
        return any(getattr(r, "_paged", False) for r in self.replicas)

    @property
    def _queue(self) -> list:
        return [q for r in self.replicas for q in r._queue]

    @property
    def _pool(self) -> _FleetPoolView:
        return _FleetPoolView(self.replicas)

    @property
    def in_flight(self) -> int:
        return sum(r.in_flight for r in self.replicas)

    # -- routing ---------------------------------------------------------

    def _head_key(self, prompt) -> tuple:
        return tuple(int(t) for t in list(prompt)[:self.affinity_window])

    def assignments(self) -> dict:
        """replica index -> [rid, ...] in routed order (the pinned trace
        the bit-identity contract replays per replica)."""
        out: dict = {i: [] for i in range(len(self.replicas))}
        for rid, ix in self.routing_trace:
            out[ix].append(rid)
        return out

    def submit(self, rid, prompt, max_new_tokens: int,
               deadline_s: float | None = None) -> int:
        """Route and submit one request; returns the replica index it
        landed on.  Raises the best (soonest-retry) rejection when every
        candidate replica rejected."""
        if rid in self._owner:
            raise ValueError(f"request id {rid!r} already in flight")
        head = self._head_key(prompt)
        snaps = [policy.snapshot_replica(
            i, r, prompt, int(max_new_tokens),
            affinity_hit=self._affinity.get(head) == i,
        ) for i, r in enumerate(self.replicas)]
        order = policy.rank_replicas(snaps)
        state = {"attempt": 0}
        rejections: list = []

        def attempt():
            ix = order[state["attempt"]]
            state["attempt"] += 1
            try:
                self.replicas[ix].submit(rid, prompt, max_new_tokens,
                                         deadline_s=deadline_s)
            except Exception as e:
                if not _is_rejection(e):
                    raise
                rejections.append(e)
                raise _Rerouted(e) from e
            return ix

        try:
            ix = retry_call(
                attempt, retries=min(self.max_reroutes, len(order) - 1),
                base_delay_s=0.0, jitter=0.0, retry_on=(_Rerouted,),
                label="fleet.route",
            )
        except (_Rerouted, RetryError):
            # every candidate rejected: surface the rejection the caller
            # can act on soonest (min retry_after_s across the fleet)
            self.stats["rejected"] += 1
            obs.inc("fleet_rejected_total")
            raise min(rejections, key=lambda e: e.retry_after_s) from None
        for e in rejections:
            # count only the rejections that caused an onward re-route
            by = self.stats["rerouted_by_reason"]
            by[e.reason] = by.get(e.reason, 0) + 1
            obs.inc("fleet_rerouted_total", reason=e.reason)
        self.stats["rerouted"] += len(rejections)
        self.stats["routed"] += 1
        obs.inc("fleet_routed_total", replica=str(ix))
        self._affinity[head] = ix
        self._owner[rid] = ix
        self.routing_trace.append((rid, ix))
        return ix

    # -- stepping --------------------------------------------------------

    def _publish_gauges(self):
        if not obs.enabled():
            return
        for i, r in enumerate(self.replicas):
            est = getattr(r, "_chunk_s", 0.0)
            mb = max(1, int(getattr(r, "max_batch", 1)))
            wait = est * (len(r._queue) / mb)
            obs.set_gauge("fleet_replica_queue_wait_s", wait,
                          replica=str(i))
            obs.set_gauge("fleet_replica_drain_pps",
                          getattr(r, "_drain_pps", 0.0), replica=str(i))

    def step(self) -> dict:
        """Step every replica with work in flight; returns the merged
        ``{rid: tokens}`` of everything that finished this step."""
        finished: dict = {}
        for r in self.replicas:
            if r.in_flight:
                finished.update(r.step())
        for rid in finished:
            self._owner.pop(rid, None)
        self._publish_gauges()
        return finished

    def drain(self, *, timeout_s: float | None = None) -> dict:
        """step() until the fleet is idle (optionally bounded)."""
        t0 = time.perf_counter()
        out: dict = {}
        while self.in_flight:
            out.update(self.step())
            if (timeout_s is not None
                    and time.perf_counter() - t0 > timeout_s):
                raise TimeoutError(
                    f"fleet drain exceeded {timeout_s}s with "
                    f"{self.in_flight} requests in flight")
        return out
