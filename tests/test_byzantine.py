"""Byzantine robustness x secure aggregation (PR: group-wise masked
aggregation, in-round attack injection, validation round gate).

Oracles, mirroring the repo's established contracts:

- in-round coalition draws and group partitions are pure functions of
  ``(seed, round)`` — jit-traced and host-replayed draws agree exactly;
- per-group masked field sums ≡ plaintext per-group integer field sums
  BIT-EXACTLY, dropout + Shamir recovery included (the group-gated
  cancellation algebra, two independent bookkeepings);
- the in-trace per-group Shamir floor and the host-side
  ``recover_grouped`` bookkeeping count the same failures round for
  round;
- ``attack=off`` / ``secagg=off`` paths are bit-identical to the
  pre-existing programs; chunked vs stacked stays within the documented
  float-sum-reorder tolerance with attacks ON;
- robust aggregators stay near the honest mean (and beat the weighted
  mean) under sign-flip / gaussian / ALIE coalitions at f < m/2.
"""

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.fl.engine import make_fl_round, make_local_sgd_update
from ddl25spring_tpu.fl.fedbuff import make_fedbuff_round
from ddl25spring_tpu.resilience import FaultPlan, ValidationGate
from ddl25spring_tpu.robust import (
    byzantine_round_mask,
    coordinate_median,
    make_alie_attack,
    make_bulyan,
    make_gaussian_attack,
    make_krum,
    make_sign_flip_attack,
    make_trimmed_mean,
    weighted_mean,
)
from ddl25spring_tpu.secagg import masks as sa_masks
from ddl25spring_tpu.secagg.protocol import SecAgg

REPO = Path(__file__).resolve().parent.parent

# same tiny logistic pattern as tests/test_fl_chunked.py: jit-cheap,
# 2 local steps so the key chain matters, ragged counts
N, PER, D, K, BS = 12, 16, 8, 4, 8
NR_SAMPLED = 8
_rng = np.random.default_rng(21)
X = _rng.normal(size=(N, PER, D)).astype(np.float32)
Y = _rng.integers(0, K, size=(N, PER)).astype(np.int32)
COUNTS = np.full((N,), PER, np.int32)
COUNTS[0] = PER - 3

P0 = {"w": jnp.zeros((D, K), jnp.float32),
      "b": jnp.zeros((K,), jnp.float32)}
KEY = jax.random.PRNGKey(3)


def loss_fn(params, xb, yb, mask, key):
    logits = xb @ params["w"] + params["b"]
    ls = -jax.nn.log_softmax(logits)[jnp.arange(yb.shape[0]), yb]
    return jnp.sum(ls * mask) / jnp.maximum(jnp.sum(mask), 1)


UPDATE = make_local_sgd_update(loss_fn, 0.05, BS, 1)


def build(**kw):
    return make_fl_round(UPDATE, X, Y, COUNTS, NR_SAMPLED,
                         device_put_data=False, **kw)


def run_rounds(rf, nr=3, p0=P0):
    p = p0
    for r in range(nr):
        p = rf(p, KEY, r)
    return p


def max_err(a, b):
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def tree_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def make_grouped_secagg(nr_groups=3, threshold_frac=0.5, seed=5,
                        clip=8.0):
    return SecAgg(N, NR_SAMPLED, counts=np.asarray(COUNTS), clip=clip,
                  threshold_frac=threshold_frac, seed=seed,
                  nr_groups=nr_groups)


# --------------------------------------------------------------------------
# byzantine_round_mask: the seeded in-round coalition draw
# --------------------------------------------------------------------------

def test_byzantine_mask_deterministic_and_varies_by_round():
    a = byzantine_round_mask(7, 3, 64, 0.3)
    b = byzantine_round_mask(7, 3, 64, 0.3)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert a.dtype == jnp.bool_ and a.shape == (64,)
    c = byzantine_round_mask(7, 4, 64, 0.3)
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    # a different seed is a different coalition stream
    d = byzantine_round_mask(8, 3, 64, 0.3)
    assert not np.array_equal(np.asarray(a), np.asarray(d))


def test_byzantine_mask_edges_and_rate():
    assert not np.asarray(byzantine_round_mask(0, 0, 16, 0.0)).any()
    assert np.asarray(byzantine_round_mask(0, 0, 16, 1.0)).all()
    # empirical rate over many rounds tracks the fraction
    hits = sum(int(np.sum(np.asarray(byzantine_round_mask(1, r, 32, 0.3))))
               for r in range(50))
    assert 0.2 < hits / (50 * 32) < 0.4


def test_byzantine_mask_traces_under_jit():
    eager = byzantine_round_mask(9, 2, 16, 0.25)
    jitted = jax.jit(
        lambda r: byzantine_round_mask(9, r, 16, 0.25)
    )(jnp.int32(2))
    assert np.array_equal(np.asarray(eager), np.asarray(jitted))


# --------------------------------------------------------------------------
# group partition: seeded, static sizes, host/trace agreement
# --------------------------------------------------------------------------

def test_group_assignment_deterministic_static_sizes():
    G = 3
    sizes = sa_masks.group_sizes(NR_SAMPLED, G)
    assert sum(sizes) == NR_SAMPLED and len(sizes) == G
    for r in range(5):
        g1 = np.asarray(sa_masks.group_assignment(5, r, NR_SAMPLED, G))
        g2 = np.asarray(sa_masks.group_assignment(5, r, NR_SAMPLED, G))
        assert np.array_equal(g1, g2)
        assert set(g1) <= set(range(G))
        # membership is random per round but sizes NEVER change (static
        # shapes inside jit depend on it)
        assert [int((g1 == g).sum()) for g in range(G)] == list(sizes)
    r0 = np.asarray(sa_masks.group_assignment(5, 0, NR_SAMPLED, G))
    r1 = np.asarray(sa_masks.group_assignment(5, 1, NR_SAMPLED, G))
    assert not np.array_equal(r0, r1)


def test_group_assignment_traces_under_jit():
    eager = sa_masks.group_assignment(5, 2, NR_SAMPLED, 3)
    jitted = jax.jit(
        lambda r: sa_masks.group_assignment(5, r, NR_SAMPLED, 3)
    )(jnp.int32(2))
    assert np.array_equal(np.asarray(eager), np.asarray(jitted))


def test_secagg_group_construction_validates():
    with pytest.raises(ValueError, match="nr_groups"):
        make_grouped_secagg(nr_groups=0)
    with pytest.raises(ValueError, match="nr_groups"):
        make_grouped_secagg(nr_groups=NR_SAMPLED + 1)
    sa = make_grouped_secagg(nr_groups=3)
    assert sa.nr_groups == 3
    assert len(sa.group_thresholds) == 3
    # per-group threshold = ceil(frac * group size), at least 1
    for t, s in zip(sa.group_thresholds, sa.group_sizes):
        assert t == max(1, -(-s * 5 // 10))
    assert "groups" in sa.describe()


# --------------------------------------------------------------------------
# grouped engine round: the per-group bit-exact oracle, tier-1 edition
# --------------------------------------------------------------------------

def test_tiny_grouped_masked_round_bit_exact_with_dropout_and_attack():
    """The tentpole end-to-end, tier-1 scale: grouped masked sums under a
    robust aggregator, seeded dropout with live Shamir recovery, an
    in-round sign-flip coalition — per-group masked sums must equal the
    plaintext per-group integer field sums BITWISE every round."""
    sa = make_grouped_secagg(nr_groups=3)
    rf = build(secagg=sa, aggregator=coordinate_median,
               attack=make_sign_flip_attack(3.0), attack_fraction=0.3,
               attack_seed=17,
               fault_plan=FaultPlan.parse("drop=0.4,seed=3"))
    params = P0
    saw_drop = False
    for r in range(4):
        field_sums, plain, nr_surv_g = rf.secagg_oracle(params, KEY, r)
        assert tree_equal(field_sums, plain), f"round {r}"
        # oracle shapes: stacked per group
        assert nr_surv_g.shape == (3,)
        for leaf in jax.tree.leaves(field_sums):
            assert leaf.shape[0] == 3 and leaf.dtype == jnp.uint32
        saw_drop |= int(jnp.sum(nr_surv_g)) < NR_SAMPLED
        params = rf(params, KEY, r)
    assert saw_drop, "seeded plan injected no drops in 4 rounds"
    assert sa.stats["rounds"] == 4
    assert (sa.stats["recovered_pair_keys"]
            + sa.stats["recovered_self_seeds"]) > 0
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(params))


def test_grouped_secagg_with_robust_aggregator_not_rejected():
    # the lifted build-time rejection: groups > 1 + robust rule builds;
    # groups == 1 + robust rule still refuses with the pinned message
    sa = make_grouped_secagg(nr_groups=4)
    rf = build(secagg=sa, aggregator=make_krum(1, 1))
    assert rf.secagg is sa
    flat = SecAgg(N, NR_SAMPLED, counts=np.asarray(COUNTS), clip=8.0,
                  threshold_frac=0.5, seed=5)
    with pytest.raises(ValueError, match="robust"):
        build(secagg=flat, aggregator=make_krum(1, 1))


def test_grouped_unmask_failures_match_in_trace_floor_round_for_round():
    """Satellite bugfix pin: the host-side per-group Shamir-floor
    bookkeeping (``recover_grouped``) must count exactly the groups the
    compiled round floored, every round.  Both sides replay the same
    seeded draws through INDEPENDENT code (host numpy bookkeeping vs the
    in-trace ``nr_surv_g >= thresholds`` predicate)."""
    # high threshold + heavy dropout so groups actually fail
    sa = make_grouped_secagg(nr_groups=3, threshold_frac=0.9)
    rf = build(secagg=sa, aggregator=coordinate_median,
               fault_plan=FaultPlan.parse("drop=0.5,seed=2"))
    thresholds = np.asarray(sa.group_thresholds)
    params = P0
    total_floored = 0
    for r in range(6):
        _, _, nr_surv_g = rf.secagg_oracle(params, KEY, r)
        floored = int((np.asarray(nr_surv_g) < thresholds).sum())
        before = sa.stats["unmask_failures"]
        params = rf(params, KEY, r)
        assert sa.stats["unmask_failures"] - before == floored, f"round {r}"
        total_floored += floored
    assert total_floored > 0, "seeded plan floored no group in 6 rounds"
    assert sa.stats["unmask_failures"] == total_floored


def test_grouped_all_groups_failed_keeps_params():
    # drop enough that some round floors EVERY group -> previous params
    # kept bit-identically, counted as a rejected round
    from ddl25spring_tpu import obs

    sa = make_grouped_secagg(nr_groups=2, threshold_frac=1.0)
    rf = build(secagg=sa, aggregator=coordinate_median,
               fault_plan=FaultPlan.parse("drop=0.6,seed=9"))
    thresholds = np.asarray(sa.group_thresholds)
    params = P0
    nr_all_failed = 0
    for r in range(6):
        _, _, nr_surv_g = rf.secagg_oracle(params, KEY, r)
        all_failed = bool((np.asarray(nr_surv_g) < thresholds).all())
        new = rf(params, KEY, r)
        if all_failed:
            nr_all_failed += 1
            assert tree_equal(new, params), f"round {r}"
        params = new
    assert nr_all_failed > 0, "seeded plan never floored every group"


def test_grouped_secagg_tracks_plaintext_grouped_mean():
    # aggregator=None reduces the decoded group sums with the group-weight
    # mean — one full-survival round must match the plaintext round within
    # the fixed-point quantization error
    sa = make_grouped_secagg(nr_groups=4)
    rf_g = build(secagg=sa)
    rf_p = build()
    pg = rf_g(P0, KEY, 0)
    pp = rf_p(P0, KEY, 0)
    assert max_err(pg, pp) <= 2 * sa.spec.quantization_error


# --------------------------------------------------------------------------
# in-round attack injection: identity, composition, host-replay exactness
# --------------------------------------------------------------------------

def test_attack_off_is_bit_identical_to_no_attack_build():
    rf_plain = build()
    rf_armed = build(attack=make_sign_flip_attack(5.0),
                     malicious_mask=np.zeros(N, bool),
                     attack_fraction=0.0)
    assert tree_equal(run_rounds(rf_plain), run_rounds(rf_armed))


def test_chunked_matches_stacked_with_attacks_on():
    # float-sum-reorder tolerance, the chunking module's documented
    # contract — attacks must not break streaming equivalence
    kw = dict(attack=make_sign_flip_attack(5.0), attack_fraction=0.3,
              attack_seed=11)
    assert max_err(run_rounds(build(**kw)),
                   run_rounds(build(client_chunk=2, **kw))) < 1e-6


def test_collusive_attack_forces_stacked_round():
    rf = build(attack=make_alie_attack(1.5), attack_fraction=0.3,
               client_chunk=2)
    assert rf.client_chunk is None  # collusive sees the whole stack


def test_in_round_draw_composes_with_dropout_and_recovers():
    # robust rule + in-round coalition + operational dropout in one round
    rf = build(aggregator=coordinate_median,
               attack=make_gaussian_attack(5.0), attack_fraction=0.3,
               attack_seed=2, fault_plan=FaultPlan.parse("drop=0.3,seed=4"))
    p = run_rounds(rf, nr=3)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(p))


def test_attack_fraction_validation():
    with pytest.raises(ValueError, match="attack_fraction"):
        build(attack_fraction=1.5, attack=make_sign_flip_attack(2.0))
    with pytest.raises(ValueError, match="attack_fraction"):
        build(attack_fraction=0.3)  # no attack to apply


def test_byzantine_counter_matches_host_replay(tmp_path):
    from ddl25spring_tpu import obs

    rf = build(attack=make_sign_flip_attack(5.0), attack_fraction=0.4,
               attack_seed=23)
    obs.enable(str(tmp_path / "t.jsonl"))
    try:
        p = P0
        for r in range(5):
            p = rf(p, KEY, r)
        snap = obs.get().snapshot()
    finally:
        obs.disable()
    expected = sum(
        int(np.sum(np.asarray(
            byzantine_round_mask(23, r, NR_SAMPLED, 0.4))))
        for r in range(5)
    )
    assert expected > 0
    got = snap["counter"]["fl_byzantine_clients_total"]["value"]
    assert got == expected


# --------------------------------------------------------------------------
# fedbuff: attack + grouped secagg on the async path
# --------------------------------------------------------------------------

def fedbuff_build(**kw):
    return make_fedbuff_round(UPDATE, X, Y, COUNTS, NR_SAMPLED,
                              staleness_window=2, **kw)


def fedbuff_run(tick, nr=3):
    h = jax.tree.map(lambda l: jnp.stack([l, l]), P0)
    for r in range(nr):
        h = tick(h, KEY, r)
    return h


def test_fedbuff_attack_off_is_bit_identical():
    plain = fedbuff_build()
    armed = fedbuff_build(attack=make_sign_flip_attack(5.0),
                          malicious_mask=np.zeros(N, bool),
                          attack_fraction=0.0)
    assert tree_equal(fedbuff_run(plain), fedbuff_run(armed))


def test_fedbuff_attack_fraction_validation():
    with pytest.raises(ValueError, match="attack"):
        fedbuff_build(attack_fraction=0.3)


def test_fedbuff_grouped_masked_tick_bit_exact_under_attack():
    sa = make_grouped_secagg(nr_groups=3, seed=8)
    tick = fedbuff_build(secagg=sa, attack=make_sign_flip_attack(3.0),
                         attack_fraction=0.3, attack_seed=5,
                         fault_plan=FaultPlan.parse("drop=0.4,seed=6"))
    h = jax.tree.map(lambda l: jnp.stack([l, l]), P0)
    for r in range(3):
        field_sums, plain, nr_surv_g = tick.secagg_oracle(h, KEY, r)
        assert tree_equal(field_sums, plain), f"tick {r}"
        assert nr_surv_g.shape == (3,)
        h = tick(h, KEY, r)
    assert sa.stats["rounds"] == 3
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(h))


# --------------------------------------------------------------------------
# robust aggregators under coalitions: bounded, and beats the mean
# --------------------------------------------------------------------------

M, DIM = 12, 24
MU = 0.5


def _coalition_stack(attack_name, key, f):
    """Honest rows ~ mu + 0.05 N(0,1); the first ``f`` rows attacked
    through the REAL attack builders (the same fns the engine vmaps).
    ALIE at the canonical stealthy z barely biases anything at this sigma,
    so the property test cranks z until the coalition measurably moves the
    mean — the contract under test is "robust rule shrugs off what the
    mean cannot", not ALIE's stealth margin."""
    k1, k2 = jax.random.split(key)
    honest = MU + 0.05 * jax.random.normal(k1, (M, DIM))
    stacked = {"w": honest}
    mal = jnp.arange(M) < f
    params = {"w": jnp.zeros((DIM,))}
    if attack_name == "alie":
        attack = make_alie_attack(30.0)
        return attack(stacked, mal, params, k2), mal
    attack = {"sign-flip": make_sign_flip_attack(5.0),
              "gaussian": make_gaussian_attack(5.0)}[attack_name]
    keys = jax.random.split(k2, M)
    adv = jax.vmap(attack, in_axes=(0, None, 0))(stacked, params, keys)
    out = jax.tree.map(
        lambda a, h: jnp.where(mal[:, None], a, h), adv, stacked
    )
    return out, mal


AGGS = [
    ("median", lambda f: coordinate_median, 5),
    ("trimmed", lambda f: make_trimmed_mean(f / M), 5),
    ("krum", lambda f: make_krum(f, 1), 5),
    ("bulyan", lambda f: make_bulyan(f), 2),  # m >= 4f+3 caps f at 2
]


@pytest.mark.parametrize("attack_name", ["sign-flip", "gaussian", "alie"])
@pytest.mark.parametrize("agg_name,make_agg,f", AGGS,
                         ids=[a[0] for a in AGGS])
def test_robust_aggregator_bounded_and_beats_mean(attack_name, agg_name,
                                                  make_agg, f):
    stacked, mal = _coalition_stack(attack_name, jax.random.PRNGKey(4), f)
    w = jnp.full((M,), 1.0 / M)
    key = jax.random.PRNGKey(9)
    agg = make_agg(f)(stacked, w, key)
    naive = weighted_mean(stacked, w, key)
    err_r = float(jnp.max(jnp.abs(agg["w"] - MU)))
    err_m = float(jnp.max(jnp.abs(naive["w"] - MU)))
    # the robust rule stays near the honest center ...
    assert err_r < 0.5, f"{agg_name} vs {attack_name}: err {err_r}"
    # ... and strictly beats the weighted mean, which the coalition moves
    assert err_m > 2 * err_r, \
        f"{agg_name} vs {attack_name}: mean {err_m} robust {err_r}"


# --------------------------------------------------------------------------
# ValidationGate
# --------------------------------------------------------------------------

def _score_of(p):
    return float(p["s"])


def test_val_gate_accepts_improving_and_skips_degrading():
    gate = ValidationGate(_score_of, policy="skip", tolerance=1.0)
    p0 = {"s": jnp.float32(10.0)}
    p1 = {"s": jnp.float32(12.0)}
    out, ok = gate.admit(0, p0, p1)
    assert ok and out is p1 and gate.best_score == 12.0
    # within tolerance: accepted, best unchanged
    p2 = {"s": jnp.float32(11.5)}
    out, ok = gate.admit(1, p1, p2)
    assert ok and out is p2 and gate.best_score == 12.0
    # below best - tolerance: skipped, previous params kept
    p3 = {"s": jnp.float32(3.0)}
    out, ok = gate.admit(2, p2, p3)
    assert not ok and out is p2
    assert gate.events == 1


def test_val_gate_restore_rolls_back_to_best():
    gate = ValidationGate(_score_of, policy="restore", tolerance=0.5)
    best = {"s": jnp.float32(20.0)}
    gate.admit(0, {"s": jnp.float32(0.0)}, best)
    worse = {"s": jnp.float32(18.0)}
    out, ok = gate.admit(1, best, worse)
    assert not ok and out is best  # rolled back to the best snapshot


def test_val_gate_clip_installs_damped_half_step():
    gate = ValidationGate(_score_of, policy="clip", tolerance=0.5)
    old = {"s": jnp.float32(10.0)}
    gate.admit(0, {"s": jnp.float32(0.0)}, old)
    bad = {"s": jnp.float32(2.0)}
    out, ok = gate.admit(1, old, bad)
    assert not ok
    assert float(out["s"]) == pytest.approx(6.0)  # old + 0.5 * (new-old)


def test_val_gate_validates_and_counts(tmp_path):
    from ddl25spring_tpu import obs

    with pytest.raises(ValueError, match="policy"):
        ValidationGate(_score_of, policy="bogus")
    with pytest.raises(ValueError, match="tolerance"):
        ValidationGate(_score_of, tolerance=-1.0)
    gate = ValidationGate(_score_of, policy="skip", tolerance=0.0)
    obs.enable(str(tmp_path / "t.jsonl"))
    try:
        gate.admit(0, {"s": jnp.float32(0.0)}, {"s": jnp.float32(5.0)})
        gate.admit(1, {"s": jnp.float32(5.0)}, {"s": jnp.float32(1.0)})
        snap = obs.get().snapshot()
    finally:
        obs.disable()
    key = 'fl_round_rejected_total{reason="val_gate"}'
    matches = [v for k, v in snap["counter"].items()
               if k.startswith("fl_round_rejected_total")]
    assert matches and matches[0]["value"] == 1


# --------------------------------------------------------------------------
# config + run_hfl guard matrix for the new flags
# --------------------------------------------------------------------------

def test_hfl_config_validates_new_fields():
    from ddl25spring_tpu.configs import HflConfig

    with pytest.raises(ValueError, match="secagg_groups"):
        HflConfig(secagg=True, secagg_groups=0)
    with pytest.raises(ValueError, match="attack_fraction"):
        HflConfig(attack="sign-flip", attack_fraction=1.5)
    with pytest.raises(ValueError, match="val_gate"):
        HflConfig(val_gate="bogus")
    with pytest.raises(ValueError, match="val_gate_tolerance"):
        HflConfig(val_gate="skip", val_gate_tolerance=-2.0)
    cfg = HflConfig(secagg=True, secagg_groups=3, attack="sign-flip",
                    attack_fraction=0.3, val_gate="restore")
    assert cfg.secagg_groups == 3


def test_run_hfl_guards_new_flag_matrix():
    from ddl25spring_tpu.configs import HflConfig
    from ddl25spring_tpu.run_hfl import build_server

    base = dict(nr_clients=12, client_fraction=0.5, nr_rounds=1)
    with pytest.raises(ValueError, match="attack-fraction"):
        build_server(HflConfig(attack_fraction=0.3, **base))
    with pytest.raises(ValueError, match="secagg-groups"):
        build_server(HflConfig(secagg_groups=2, **base))
    with pytest.raises(ValueError, match="val-gate"):
        build_server(HflConfig(val_gate="skip", algorithm="centralized",
                               nr_rounds=1))
    # groups == 1 + robust aggregator: still the pinned rejection,
    # now pointing at group mode
    with pytest.raises(ValueError, match="robust aggregator"):
        build_server(HflConfig(secagg=True, aggregator="krum", **base))
    # fedbuff has no robust hook even in group mode
    with pytest.raises(ValueError, match="fedbuff"):
        build_server(HflConfig(secagg=True, secagg_groups=2,
                               aggregator="median", algorithm="fedbuff",
                               **base))


def test_run_hfl_builds_grouped_robust_server_with_gate():
    from ddl25spring_tpu.configs import HflConfig
    from ddl25spring_tpu.run_hfl import build_server

    server = build_server(HflConfig(
        secagg=True, secagg_groups=3, aggregator="median",
        attack="sign-flip", attack_fraction=0.3,
        nr_clients=12, client_fraction=0.5, nr_rounds=1,
    ))
    assert server.round_fn.secagg.nr_groups == 3
    # the gate is installed post-build by run(); servers expose the slot
    assert server.val_gate is None


# --------------------------------------------------------------------------
# MNIST-scale: grouped masked rounds bit-exact for EVERY server type
# --------------------------------------------------------------------------

NR_CLIENTS_MNIST, COHORT_MNIST, G_MNIST = 16, 8, 3


@pytest.fixture(scope="module")
def mnist_parts():
    from ddl25spring_tpu.data import load_mnist, split_dataset
    from ddl25spring_tpu.fl import mnist_task

    ds = load_mnist(n_train=512, n_test=128)
    task = mnist_task(ds.test_x, ds.test_y)
    clients = split_dataset(ds.train_x, ds.train_y,
                            nr_clients=NR_CLIENTS_MNIST, iid=True, seed=0,
                            pad_multiple=32)
    clients1 = split_dataset(ds.train_x, ds.train_y,
                             nr_clients=NR_CLIENTS_MNIST, iid=True, seed=0,
                             pad_multiple=1)
    return task, clients, clients1


def _mnist_grouped_secagg(client_data):
    return SecAgg(NR_CLIENTS_MNIST, COHORT_MNIST,
                  counts=np.asarray(client_data.counts), clip=4.0,
                  threshold_frac=0.5, seed=3, nr_groups=G_MNIST)


def _assert_grouped_bit_exact(server, sa, nr_rounds=3):
    rf = server.round_fn
    params = server.params
    nr_dropped = 0
    for r in range(nr_rounds):
        field_sums, plain, nr_surv_g = rf.secagg_oracle(
            params, server.run_key, r)
        assert tree_equal(field_sums, plain), f"round {r}"
        assert nr_surv_g.shape == (G_MNIST,)
        if int(jnp.sum(nr_surv_g)) < COHORT_MNIST:
            nr_dropped += 1
        params = rf(params, server.run_key, r)
    assert sa.stats["rounds"] == nr_rounds
    return nr_dropped


DROP_PLAN = "drop=0.3,seed=11"
ATTACK_KW = dict(attack=make_sign_flip_attack(3.0), attack_fraction=0.3,
                 attack_seed=13)


@pytest.mark.slow  # MNIST-scale compile; the tiny tier-1 round covers the path
def test_fedavg_grouped_secagg_robust_bit_exact(mnist_parts):
    from ddl25spring_tpu.fl import FedAvgServer

    task, clients, _ = mnist_parts
    sa = _mnist_grouped_secagg(clients)
    srv = FedAvgServer(task, 0.05, 32, clients, 0.5, 1, 3,
                       secagg=sa, aggregator=coordinate_median,
                       fault_plan=FaultPlan.parse(DROP_PLAN), **ATTACK_KW)
    dropped = _assert_grouped_bit_exact(srv, sa, nr_rounds=4)
    assert dropped > 0, "seeded plan injected no drops in 4 rounds"
    assert (sa.stats["recovered_pair_keys"]
            + sa.stats["recovered_self_seeds"]) > 0


@pytest.mark.slow  # MNIST-scale compile; the tiny tier-1 round covers the path
def test_fedsgd_gradient_grouped_secagg_robust_bit_exact(mnist_parts):
    from ddl25spring_tpu.fl import FedSgdGradientServer

    task, _, clients1 = mnist_parts
    sa = _mnist_grouped_secagg(clients1)
    srv = FedSgdGradientServer(task, 0.05, clients1, 0.5, 3,
                               secagg=sa, aggregator=coordinate_median,
                               fault_plan=FaultPlan.parse(DROP_PLAN),
                               **ATTACK_KW)
    _assert_grouped_bit_exact(srv, sa)


@pytest.mark.slow  # MNIST-scale compile; the tiny tier-1 round covers the path
def test_fedsgd_weight_grouped_secagg_robust_bit_exact(mnist_parts):
    from ddl25spring_tpu.fl import FedSgdWeightServer

    task, _, clients1 = mnist_parts
    sa = _mnist_grouped_secagg(clients1)
    srv = FedSgdWeightServer(task, 0.05, clients1, 0.5, 3,
                             secagg=sa, aggregator=coordinate_median,
                             fault_plan=FaultPlan.parse(DROP_PLAN),
                             **ATTACK_KW)
    _assert_grouped_bit_exact(srv, sa)


@pytest.mark.slow  # MNIST-scale compile; the tiny tier-1 round covers the path
def test_fedopt_grouped_secagg_robust_bit_exact(mnist_parts):
    from ddl25spring_tpu.fl import FedOptServer

    task, clients, _ = mnist_parts
    sa = _mnist_grouped_secagg(clients)
    srv = FedOptServer(task, 0.05, 32, clients, 0.5, 1, 3,
                       server_optimizer="adam", server_lr=0.01,
                       secagg=sa, aggregator=coordinate_median,
                       fault_plan=FaultPlan.parse(DROP_PLAN), **ATTACK_KW)
    assert srv.round_fn.secagg is sa
    _assert_grouped_bit_exact(srv, sa)


@pytest.mark.slow  # MNIST-scale compile; the tiny tier-1 round covers the path
def test_fedbuff_grouped_secagg_bit_exact(mnist_parts):
    # fedbuff has no robust-aggregator hook: grouped sessions recombine by
    # staleness weight, so no aggregator kwarg here — attack still applies
    from ddl25spring_tpu.fl.fedbuff import FedBuffServer

    task, clients, _ = mnist_parts
    sa = _mnist_grouped_secagg(clients)
    srv = FedBuffServer(task, 0.05, 32, clients, 0.5, 1, 3,
                        staleness_window=3, secagg=sa,
                        fault_plan=FaultPlan.parse(DROP_PLAN), **ATTACK_KW)
    rf = srv.round_fn
    h = srv.params
    for r in range(3):
        field_sums, plain, nr_surv_g = rf.secagg_oracle(h, srv.run_key, r)
        assert tree_equal(field_sums, plain), f"tick {r}"
        assert nr_surv_g.shape == (G_MNIST,)
        h = rf(h, srv.run_key, r)
    assert sa.stats["rounds"] == 3


# --------------------------------------------------------------------------
# scenario matrix: the smoke cells ARE the acceptance demonstration
# --------------------------------------------------------------------------

def test_scenario_matrix_smoke_shows_robust_recovery(tmp_path):
    """30%% sign-flip coalition: the weighted mean degrades while the
    robust defense stack (median over decoded aggregates + validation
    gate) recovers final accuracy — in plain AND secagg-grouped mode."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import scenario_matrix
    finally:
        sys.path.pop(0)
    rc = scenario_matrix.main([
        "--smoke", "--out", str(tmp_path), "--nr-rounds", "30",
    ])
    assert rc == 0
    rows = {}
    for cell in ("sign-flip_mean_plain_c8", "sign-flip_mean_secagg_c8",
                 "sign-flip_median_plain_c8",
                 "sign-flip_median_secagg_c8"):
        res = json.loads((tmp_path / f"{cell}.json").read_text())
        assert "skipped" not in res, cell
        rows[cell] = res
    for mode in ("plain", "secagg"):
        mean_acc = rows[f"sign-flip_mean_{mode}_c8"]["final_accuracy"]
        rob_acc = rows[f"sign-flip_median_{mode}_c8"]["final_accuracy"]
        assert rob_acc >= 70.0, (mode, rob_acc)
        assert mean_acc <= rob_acc - 15.0, (mode, mean_acc, rob_acc)
    # the grouped cell really ran grouped sessions with live stats
    g = rows["sign-flip_median_secagg_c8"]
    assert g.get("secagg_groups", 0) > 1
    assert g["secagg_stats"]["rounds"] == 30
    assert (tmp_path / "summary.json").exists()
