"""CIFAR-10 loader with deterministic synthetic fallback.

CIFAR-10 is the north-star FL benchmark dataset (BASELINE.json: FedAvg,
256 clients, ResNet-18).  The reference never ships it (it targets MNIST);
we follow the same real-if-present / synthetic-otherwise policy as
:mod:`ddl25spring_tpu.data.mnist`.
"""

from __future__ import annotations

import pickle

import numpy as np

from .mnist import (
    DatasetNotFound,
    ImageDataset,
    announce_synthetic_fallback,
    candidate_data_dirs,
    raw_dataset,
    synthetic_image_dataset,
)

CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], dtype=np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], dtype=np.float32)

_candidate_dirs = candidate_data_dirs


def _normalize(x_uint8: np.ndarray) -> np.ndarray:
    x = x_uint8.astype(np.float32) / 255.0
    return (x - CIFAR_MEAN) / CIFAR_STD


def cifar_input_transform(dtype=None):
    """On-device normalizer for ``load_cifar10(raw=True)`` uint8 batches
    (see data.mnist.make_input_transform / raw_dataset)."""
    from .mnist import make_input_transform

    return make_input_transform(CIFAR_MEAN, CIFAR_STD, dtype)


def _try_load_real(raw: bool = False) -> ImageDataset | None:
    for root in _candidate_dirs():
        npz = root / "cifar10.npz"
        if npz.exists():
            d = np.load(npz)
            if raw:
                return raw_dataset(d["train_x"], d["train_y"],
                                   d["test_x"], d["test_y"], synthetic=False)
            return ImageDataset(
                train_x=_normalize(d["train_x"]),
                train_y=d["train_y"].astype(np.int32),
                test_x=_normalize(d["test_x"]),
                test_y=d["test_y"].astype(np.int32),
                synthetic=False,
            )
        batch_dir = root / "cifar-10-batches-py"
        if (batch_dir / "data_batch_1").exists():
            def load_batch(p):
                with open(p, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
                return x, np.array(d[b"labels"], dtype=np.int32)

            xs, ys = zip(*[load_batch(batch_dir / f"data_batch_{i}") for i in range(1, 6)])
            test_x, test_y = load_batch(batch_dir / "test_batch")
            if raw:
                return raw_dataset(np.concatenate(xs), np.concatenate(ys),
                                   test_x, test_y, synthetic=False)
            return ImageDataset(
                train_x=_normalize(np.concatenate(xs)),
                train_y=np.concatenate(ys),
                test_x=_normalize(test_x),
                test_y=test_y,
                synthetic=False,
            )
    return None


def load_cifar10(
    synthetic_fallback: bool = True,
    n_train: int = 50000,
    n_test: int = 10000,
    seed: int = 1,
    raw: bool = False,
) -> ImageDataset:
    """``raw=True`` returns uint8 images (no normalization) — same pixels,
    same rng stream as the normalized dataset for a given seed; normalize
    on device with :func:`cifar_input_transform`."""
    real = _try_load_real(raw=raw)
    if real is not None:
        return real
    if not synthetic_fallback:
        raise DatasetNotFound(
            "CIFAR-10 not found; set DDL25_DATA_DIR to a directory containing "
            "cifar10.npz or cifar-10-batches-py"
        )
    announce_synthetic_fallback("cifar10")
    return synthetic_image_dataset(
        n_train=n_train, n_test=n_test, size=32, nr_classes=10,
        channels=3, noise=0.3, max_shift=4, seed=seed,
        mean=CIFAR_MEAN, std=CIFAR_STD, raw=raw,
    )
