"""Tiled aggregation-kernel tests (ops/pairwise.py + secagg/kernels.py).

Two parity ladders, each anchored to a reference with independent
bookkeeping:

- the pairwise distance pass: naive broadcast vs XLA Gram identity vs the
  blockwise Pallas kernel (interpret mode on CPU, compiled under the
  TPU-only @slow tests) — plus the decision-level oracle that krum/bulyan
  pick IDENTICAL winners whichever backend scored the distances;
- the fused secagg masked-sum kernel vs the separate-ops XLA graph
  (encode -> cohort masks -> weighted survivor sum), asserted BITWISE:
  the two sides share only the counter PRG and the encode arithmetic, so
  agreement checks the fused kernel's gating/reduction algebra rather
  than restating it.  The end-to-end masked == plaintext oracles then run
  through the real engine rounds (tiny tier-1 + all five server types
  @slow) with seeded dropout so Shamir recovery is live.

The donation-gate matrix pins the jax-0.4.37 cache interaction
(``engine.donation_safe``) and the observable buffer-deletion behavior the
run_hfl donate predicate relies on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.fl.engine import donation_safe, make_fl_round
from ddl25spring_tpu.ops import pairwise
from ddl25spring_tpu.resilience.faults import FaultPlan
from ddl25spring_tpu.robust.aggregators import make_bulyan, make_krum
from ddl25spring_tpu.secagg import kernels as sa_kernels
from ddl25spring_tpu.secagg import masks as sa_masks
from ddl25spring_tpu.secagg.field import FieldSpec, encode
from ddl25spring_tpu.secagg.protocol import SecAgg

ON_TPU = jax.default_backend() == "tpu"

IMPLS = ("naive", "gram", "pallas")


def trees_bitwise_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        (np.asarray(x) == np.asarray(y)).all() for x, y in zip(la, lb)
    )


# --------------------------------------------------------------------------
# ops/pairwise.py: three implementations, one (m, m) answer
# --------------------------------------------------------------------------

def _rand(m, d, dtype, seed=0):
    x = jax.random.normal(jax.random.PRNGKey(seed), (m, d), jnp.float32)
    return x.astype(dtype)


# tolerance matrix: the naive form subtracts BEFORE squaring while the Gram
# identity subtracts two O(d)-sized sums, so their float32 round-off
# differs by O(d * eps * scale); distances here are O(2d).  bf16 inputs are
# upcast (all impls see identical f32 values), so the same bound holds.
PAIR_TOL = {
    jnp.dtype(jnp.float32): 5e-3,
    jnp.dtype(jnp.bfloat16): 5e-3,
}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("shape", [(12, 48), (8, 1024), (256, 512)])
def test_pairwise_parity_matrix(dtype, shape):
    # (8, 1024) forces two feature blocks, (256, 512) two m-blocks in the
    # Pallas grid; interpret mode keeps this off-TPU-safe (tier-1)
    m, d = shape
    mat = _rand(m, d, dtype)
    ref = pairwise.pairwise_sq_dists(mat, impl="naive")
    assert ref.dtype == jnp.float32 and ref.shape == (m, m)
    # symmetric, zero diagonal, clamped at zero
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ref).T,
                               atol=PAIR_TOL[jnp.dtype(dtype)])
    assert float(jnp.min(ref)) >= 0.0
    assert float(jnp.max(jnp.abs(jnp.diag(ref)))) == 0.0
    for impl in ("gram", "pallas"):
        got = pairwise.pairwise_sq_dists(mat, impl=impl, interpret=None
                                         if ON_TPU else True)
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref),
            atol=PAIR_TOL[jnp.dtype(dtype)],
            err_msg=f"impl={impl} dtype={dtype} shape={shape}",
        )


def test_pairwise_int8_stack_is_exact_across_impls():
    # int8 values in [-64, 63] at d=256 keep every partial sum an integer
    # below 2^24, so f32 accumulation is EXACT regardless of association —
    # all three implementations must agree bitwise (this is the
    # robust_stack="int8" storage path)
    rng = np.random.default_rng(3)
    mat = jnp.asarray(rng.integers(-64, 64, size=(16, 256)), jnp.int8)
    outs = [np.asarray(pairwise.pairwise_sq_dists(mat, impl=i))
            for i in IMPLS]
    assert np.array_equal(outs[0], outs[1])
    assert np.array_equal(outs[0], outs[2])


def test_pairwise_validates_inputs():
    with pytest.raises(ValueError, match="impl="):
        pairwise.pairwise_sq_dists(jnp.zeros((4, 4)), impl="fft")
    with pytest.raises(ValueError, match="must be"):
        pairwise.pairwise_sq_dists(jnp.zeros((4,)))


def test_dist_pass_bytes_model():
    m, d = 64, 4096
    naive = pairwise.dist_pass_bytes(m, d, impl="naive")
    gram = pairwise.dist_pass_bytes(m, d, impl="gram")
    pallas = pairwise.dist_pass_bytes(m, d, impl="pallas")
    # the whole point of the rewrite: the naive peak carries the m²·d term,
    # the other two don't (their peaks are d-independent / tile-bounded)
    assert naive["peak_intermediate"] == m * m * d * 4
    assert gram["peak_intermediate"] < naive["peak_intermediate"]
    assert pallas["peak_intermediate"] < naive["peak_intermediate"]
    assert (pairwise.dist_pass_bytes(m, 8 * d, impl="gram")
            ["peak_intermediate"] == gram["peak_intermediate"])
    # reduced-precision storage reduces traffic for the tiled kernel (it
    # upcasts per-tile in VMEM) and adds a one-shot upcast copy for gram
    assert (pairwise.dist_pass_bytes(m, d, impl="pallas", itemsize=1)
            ["moved"] < pallas["moved"])
    assert (pairwise.dist_pass_bytes(m, d, impl="gram", itemsize=2)
            ["peak_intermediate"] > gram["peak_intermediate"])
    with pytest.raises(ValueError, match="impl="):
        pairwise.dist_pass_bytes(m, d, impl="blocked")


# --------------------------------------------------------------------------
# decision identity: the backends may round differently, the ROBUST RULE
# must not care (acceptance: bit-identical winners)
# --------------------------------------------------------------------------

def _outlier_stack(m, seed=0, dtype=jnp.float32):
    """Honest cluster + 2 planted outliers, as a two-leaf pytree."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(m, 4, 3)).astype(np.float32)
    b = rng.normal(size=(m, 5)).astype(np.float32)
    w[:2] += 40.0
    b[:2] -= 40.0
    return {"w": jnp.asarray(w, dtype), "b": jnp.asarray(b, dtype)}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_krum_decision_identity_across_impls(dtype):
    stacked = _outlier_stack(12, dtype=dtype)
    outs = [make_krum(2, nr_selected=3, pairwise_impl=i)(stacked)
            for i in IMPLS]
    assert trees_bitwise_equal(outs[0], outs[1])
    assert trees_bitwise_equal(outs[0], outs[2])
    # and the rule actually did its job: the planted outliers lost
    assert float(jnp.max(jnp.abs(outs[0]["w"]))) < 10.0


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bulyan_decision_identity_across_impls(dtype):
    stacked = _outlier_stack(11, dtype=dtype)  # m >= 4f + 3 at f = 2
    outs = [make_bulyan(2, pairwise_impl=i)(stacked) for i in IMPLS]
    assert trees_bitwise_equal(outs[0], outs[1])
    assert trees_bitwise_equal(outs[0], outs[2])
    assert float(jnp.max(jnp.abs(outs[0]["b"]))) < 10.0


def test_robust_rules_expose_pairwise_impl():
    # the telemetry hook the round loop reads for fl_aggregator_dist_bytes
    assert make_krum(1).pairwise_impl == "auto"
    assert make_bulyan(1, pairwise_impl="gram").pairwise_impl == "gram"


# --------------------------------------------------------------------------
# the counter PRG: one function, both mask sides
# --------------------------------------------------------------------------

def test_counter_prg_deterministic_and_domain_separated():
    base = sa_kernels.counter_base(7, 3, 1)
    assert base.dtype == jnp.uint32
    offs = jnp.arange(8, dtype=jnp.uint32)
    bits = sa_kernels.counter_bits(base, offs)
    assert np.array_equal(np.asarray(bits),
                          np.asarray(sa_kernels.counter_bits(base, offs)))
    # every input coordinate separates the stream
    for other in (sa_kernels.counter_base(8, 3, 1),
                  sa_kernels.counter_base(7, 4, 1),
                  sa_kernels.counter_base(7, 3, 2)):
        assert not np.array_equal(
            np.asarray(bits),
            np.asarray(sa_kernels.counter_bits(other, offs)),
        )
    # broadcasting contract the kernel relies on: (m, 1) x (1, bl) tile
    tile = sa_kernels.counter_bits(
        sa_kernels.counter_base(jnp.arange(5, dtype=jnp.uint32), 0, 0)
        [:, None],
        offs[None, :],
    )
    assert tile.shape == (5, 8) and tile.dtype == jnp.uint32
    # rows are distinct streams (distinct bases)
    assert len({tuple(r) for r in np.asarray(tile)}) == 5


def test_mask_pass_bytes_model():
    m, length = 32, 8192
    fused = sa_kernels.mask_pass_bytes(m, length)
    xla = sa_kernels.mask_pass_bytes(m, length, impl="xla")
    # fused reads the stack once and writes the sums; the XLA graph
    # round-trips the encoded/mask/masked (m, length) trees on top
    assert fused["moved"] < xla["moved"]
    assert fused["peak_intermediate"] == m * sa_kernels.BLOCK_L * 4
    assert xla["peak_intermediate"] == 3 * m * length * 4
    with pytest.raises(ValueError, match="impl="):
        sa_kernels.mask_pass_bytes(m, length, impl="mosaic")


# --------------------------------------------------------------------------
# fused kernel vs the separate-ops XLA graph, bitwise
# --------------------------------------------------------------------------

def _xla_masked_sums(msgs, spec, seed, gids, live, surv, omega_u, round_idx,
                     groups=None, nr_groups=1):
    """The reference graph the engine's non-fused branch runs: separate
    encode, cohort-mask and weighted-survivor-sum ops (mirrored here, not
    imported, so the test keeps its own bookkeeping)."""
    def wrow(t, v):
        return v.reshape((-1,) + (1,) * (t.ndim - 1))

    template = jax.tree.map(lambda l: l[0], msgs)
    enc = encode(msgs, spec)
    cohort = sa_masks.cohort_masks(seed, gids, live, jnp.int32(round_idx),
                                   template, groups=groups)
    masked = jax.tree.map(
        lambda e, mk: e * wrow(e, jnp.asarray(omega_u, jnp.uint32)) + mk,
        enc, cohort,
    )
    if groups is None:
        groups = jnp.zeros((gids.shape[0],), jnp.int32)

    def gsum(ml):
        contrib = jnp.where(wrow(ml, surv), ml, jnp.uint32(0))
        return jnp.zeros((nr_groups,) + ml.shape[1:], jnp.uint32
                         ).at[groups].add(contrib)

    return jax.tree.map(gsum, masked)


def _fused_case(seed=11):
    m = 6
    rng = np.random.default_rng(seed)
    w = rng.normal(scale=3.0, size=(m, 5, 3)).astype(np.float32)
    b = rng.normal(scale=3.0, size=(m, 7)).astype(np.float32)
    # the kernel's in-pass sanitise/clamp must match field.encode exactly
    w[0, 0, 0], w[1, 0, 1], b[2, 0] = np.nan, np.inf, -np.inf
    msgs = {"w": jnp.asarray(w), "b": jnp.asarray(b)}
    gids = jnp.asarray([9, 2, 14, 0, 7, 11])
    live = jnp.asarray([True, True, True, False, True, True])
    surv = jnp.asarray([True, False, True, False, True, False])
    counts = jnp.asarray([4, 8, 2, 5, 6, 3], jnp.uint32)
    omega_u = jnp.where(live, counts, 0).astype(jnp.uint32)
    spec = FieldSpec.for_budget(4.0, int(counts.sum()))
    return msgs, spec, gids, live, surv, omega_u


def test_fused_masked_sums_matches_xla_flat_bitwise():
    msgs, spec, gids, live, surv, omega_u = _fused_case()
    for r in (0, 3):
        fused = sa_kernels.fused_masked_sums(
            msgs, spec, 5, gids, live, surv, omega_u, r, interpret=True
        )
        assert all(l.shape[0] == 1 for l in jax.tree.leaves(fused))
        ref = _xla_masked_sums(msgs, spec, 5, gids, live, surv, omega_u, r)
        assert trees_bitwise_equal(fused, ref), f"round {r}"


def test_fused_masked_sums_matches_xla_grouped_bitwise():
    msgs, spec, gids, live, surv, omega_u = _fused_case(seed=4)
    groups = jnp.asarray([0, 1, 2, 0, 1, 2], jnp.int32)
    fused = sa_kernels.fused_masked_sums(
        msgs, spec, 9, gids, live, surv, omega_u, 2,
        groups=groups, nr_groups=3, interpret=True,
    )
    ref = _xla_masked_sums(msgs, spec, 9, gids, live, surv, omega_u, 2,
                           groups=groups, nr_groups=3)
    assert trees_bitwise_equal(fused, ref)
    # group gating is load-bearing: a cross-group assignment changes sums
    other = sa_kernels.fused_masked_sums(
        msgs, spec, 9, gids, live, surv, omega_u, 2,
        groups=jnp.asarray([0, 0, 1, 1, 2, 2], jnp.int32), nr_groups=3,
        interpret=True,
    )
    assert not trees_bitwise_equal(fused, other)


def test_fused_kernel_feature_padding_is_inert():
    # 600 is not a multiple of BLOCK_L: the kernel pads, masks the pad
    # offsets like real columns, then slices them off — the visible sums
    # must still match the unpadded XLA graph bitwise
    m = 4
    rng = np.random.default_rng(0)
    msgs = {"x": jnp.asarray(rng.normal(size=(m, 600)), jnp.float32)}
    gids = jnp.asarray([3, 1, 6, 0])
    live = jnp.asarray([True, True, True, True])
    surv = jnp.asarray([True, True, False, True])
    omega_u = jnp.full((m,), 2, jnp.uint32)
    spec = FieldSpec.for_budget(4.0, 8)
    fused = sa_kernels.fused_masked_sums(
        msgs, spec, 1, gids, live, surv, omega_u, 0, interpret=True
    )
    ref = _xla_masked_sums(msgs, spec, 1, gids, live, surv, omega_u, 0)
    assert trees_bitwise_equal(fused, ref)


# --------------------------------------------------------------------------
# engine wiring: fused rounds are THE SAME rounds (tiny, tier-1)
# --------------------------------------------------------------------------

def _tiny_round(secagg, secagg_impl, nr_clients=12, n_i=4, d=6):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(nr_clients, n_i, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(nr_clients, n_i)), jnp.float32)
    counts = jnp.full((nr_clients,), n_i, jnp.int32)

    def client_update(params, xi, yi, ci, key):
        resid = xi @ params["w"] - yi
        return {"w": params["w"] - 0.1 * (xi.T @ resid / n_i)}

    rf = make_fl_round(client_update, x, y, counts, nr_sampled=6,
                       secagg=secagg, secagg_impl=secagg_impl,
                       fault_plan=FaultPlan.parse("drop=0.4,seed=3"))
    return rf, {"w": jnp.zeros((d,), jnp.float32)}


def _tiny_secagg(nr_groups=1, seed=5):
    return SecAgg(12, 6, counts=np.full(12, 4), clip=4.0,
                  threshold_frac=0.5, seed=seed, nr_groups=nr_groups)


def test_tiny_fused_round_bit_exact_and_matches_xla():
    """The load-bearing end-to-end oracle at tier-1 scale: with the fused
    kernel forced (interpret mode on CPU), every round's masked field sum
    equals the no-mask plaintext sum bitwise, AND the whole parameter
    trajectory is bit-identical to the XLA-graph backend — under seeded
    dropout, so Shamir recovery runs on both."""
    rf_f, params_f = _tiny_round(_tiny_secagg(), "fused")
    rf_x, params_x = _tiny_round(_tiny_secagg(), "xla")
    assert rf_f.secagg_fused is True
    assert rf_x.secagg_fused is False
    key = jax.random.PRNGKey(42)
    saw_drop = False
    for r in range(4):
        fs_f, plain_f, nr_surv = rf_f.secagg_oracle(params_f, key, r)
        fs_x, plain_x, _ = rf_x.secagg_oracle(params_x, key, r)
        assert trees_bitwise_equal(fs_f, plain_f), f"round {r}"
        assert trees_bitwise_equal(fs_f, fs_x), f"round {r}"
        assert trees_bitwise_equal(plain_f, plain_x), f"round {r}"
        saw_drop |= int(nr_surv) < 6
        params_f = rf_f(params_f, key, r)
        params_x = rf_x(params_x, key, r)
        assert trees_bitwise_equal(params_f, params_x), f"round {r}"
    assert saw_drop, "seeded plan injected no drops in 4 rounds"
    assert np.isfinite(np.asarray(params_f["w"])).all()


def test_tiny_fused_grouped_round_bit_exact_and_matches_xla():
    rf_f, params = _tiny_round(_tiny_secagg(nr_groups=3), "fused")
    rf_x, _ = _tiny_round(_tiny_secagg(nr_groups=3), "xla")
    key = jax.random.PRNGKey(7)
    for r in range(3):
        fs_f, plain_f, nr_surv_g = rf_f.secagg_oracle(params, key, r)
        fs_x, plain_x, _ = rf_x.secagg_oracle(params, key, r)
        assert nr_surv_g.shape == (3,)
        assert trees_bitwise_equal(fs_f, plain_f), f"round {r}"
        assert trees_bitwise_equal(fs_f, fs_x), f"round {r}"
        new_f = rf_f(params, key, r)
        new_x = rf_x(params, key, r)
        assert trees_bitwise_equal(new_f, new_x), f"round {r}"
        params = new_f


def test_secagg_impl_validation():
    from ddl25spring_tpu.configs import HflConfig
    from ddl25spring_tpu.fl.fedbuff import make_fedbuff_round

    with pytest.raises(ValueError, match="secagg_impl="):
        _tiny_round(None, "mosaic")
    with pytest.raises(ValueError, match="secagg_impl must be"):
        HflConfig(secagg_impl="bogus")
    with pytest.raises(ValueError, match="secagg_impl="):
        make_fedbuff_round(
            lambda p, x, y, c, k: p, jnp.zeros((4, 2, 3)),
            jnp.zeros((4, 2), jnp.int32), jnp.full((4,), 2, jnp.int32),
            nr_sampled=2, secagg_impl="tpu",
        )
    # default config validates and resolves off-TPU to the XLA graph
    assert HflConfig(secagg=True).secagg_impl == "auto"
    rf, _ = _tiny_round(_tiny_secagg(), "auto")
    assert rf.secagg_fused is ON_TPU


# --------------------------------------------------------------------------
# donation gate matrix (engine.donation_safe + observable deletion)
# --------------------------------------------------------------------------

def test_donation_safe_gates_on_persistent_cache():
    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        assert donation_safe((0,)) == (0,)
        assert donation_safe(()) == ()
        # the jax-0.4.37 hazard: deserialized executables can lose
        # read-before-write ordering on donated buffers, so any persistent
        # cache dir disables donation wholesale
        jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache-test")
        assert donation_safe((0,)) == ()
        assert donation_safe(()) == ()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_round_donation_matrix(tmp_path):
    """donate=True deletes the input params buffer (enforced on CPU too);
    donate=False keeps it; donate=True UNDER a persistent compilation
    cache is silently gated off — the exact matrix run_hfl's donate
    predicate and docs/PERFORMANCE.md document."""
    def build(donate):
        sa = None
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 4, 6)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        counts = jnp.full((8,), 4, jnp.int32)

        def cu(params, xi, yi, ci, key):
            resid = xi @ params["w"] - yi
            return {"w": params["w"] - 0.1 * (xi.T @ resid / 4)}

        return make_fl_round(cu, x, y, counts, nr_sampled=4,
                             client_chunk=2, donate=donate, secagg=sa)

    key = jax.random.PRNGKey(0)
    # conftest.py enables the persistent compilation cache session-wide
    # (which is itself the gate under test), so each cell pins the config
    # it wants at BUILD time — donation_safe resolves in the jit decorator
    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        rf_donating = build(donate=True)
        rf_plain = build(donate=False)
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        rf_gated = build(donate=True)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)

    p = {"w": jnp.zeros((6,), jnp.float32)}
    leaf = p["w"]
    rf_donating(p, key, 0)
    assert leaf.is_deleted()

    p = {"w": jnp.zeros((6,), jnp.float32)}
    leaf = p["w"]
    rf_plain(p, key, 0)
    assert not leaf.is_deleted()

    p = {"w": jnp.zeros((6,), jnp.float32)}
    leaf = p["w"]
    rf_gated(p, key, 0)
    assert not leaf.is_deleted()


# --------------------------------------------------------------------------
# telemetry: the distance pass is accounted per round
# --------------------------------------------------------------------------

def test_krum_round_sets_dist_bytes_gauge(tmp_path):
    from ddl25spring_tpu import obs

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(12, 4, 6)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(12, 4)), jnp.float32)
    counts = jnp.full((12,), 4, jnp.int32)

    def cu(params, xi, yi, ci, key):
        resid = xi @ params["w"] - yi
        return {"w": params["w"] - 0.1 * (xi.T @ resid / 4)}

    rf = make_fl_round(cu, x, y, counts, nr_sampled=8,
                       aggregator=make_krum(2))
    params = {"w": jnp.zeros((6,), jnp.float32)}
    obs.enable(str(tmp_path / "t.jsonl"))
    try:
        rf(params, jax.random.PRNGKey(0), 0)
        snap = obs.get().snapshot()
    finally:
        obs.disable()
    got = snap["gauge"]["fl_aggregator_dist_bytes"]["value"]
    # f32 stack of 6 coordinates over the (possibly mesh-padded) cohort,
    # through whatever backend "auto" resolved to on this host
    assert got == pairwise.dist_pass_bytes(
        rf.nr_sampled, 6, impl="auto", itemsize=4
    )["moved"]


# --------------------------------------------------------------------------
# all five server types, fused backend (@slow)
# --------------------------------------------------------------------------
# A small linear softmax task over synthetic data, NOT MNIST: the battery
# exercises the five servers' secagg_impl WIRING (sampling, fault masks,
# FedOpt's wrapped round, FedBuff's tick), which is model-size-independent
# — and the interpret-mode fused kernel is pathologically slow inside
# MNIST-sized XLA:CPU round programs (minutes per round at P~8k, seconds
# here).  Compiled-kernel scale lives in the TPU-only tests below.

NR_CLIENTS = 16
COHORT = 8
DROP_PLAN = "drop=0.3,seed=11"


@pytest.fixture(scope="module")
def task_and_clients():
    from ddl25spring_tpu.data import split_dataset
    from ddl25spring_tpu.fl.task import Task

    d, k = 32, 10
    rng = np.random.default_rng(0)
    train_x = rng.normal(size=(256, d)).astype(np.float32)
    train_y = rng.integers(0, k, size=(256,)).astype(np.int32)

    def init(key):
        return {"w": jnp.zeros((d, k), jnp.float32),
                "b": jnp.zeros((k,), jnp.float32)}

    def loss_fn(params, xb, yb, mask, key):
        logits = xb @ params["w"] + params["b"]
        ls = -jax.nn.log_softmax(logits)[jnp.arange(yb.shape[0]), yb]
        return jnp.sum(ls * mask) / jnp.maximum(jnp.sum(mask), 1)

    def score_fn(params, xb):
        return xb @ params["w"] + params["b"]

    task = Task(init=init, loss_fn=loss_fn, score_fn=score_fn,
                test_x=jnp.asarray(train_x[:64]),
                test_y=jnp.asarray(train_y[:64]))
    clients = split_dataset(train_x, train_y, nr_clients=NR_CLIENTS,
                            iid=True, seed=0, pad_multiple=8)
    return task, clients


def _battery_secagg(clients, nr_groups=1):
    return SecAgg(NR_CLIENTS, COHORT, counts=np.asarray(clients.counts),
                  clip=4.0, threshold_frac=0.5, seed=3,
                  nr_groups=nr_groups)


def _assert_fused_bit_exact(srv, nr_rounds=3):
    rf = srv.round_fn
    assert rf.secagg_fused is True
    params = srv.params
    for r in range(nr_rounds):
        field_sum, plain, _ = rf.secagg_oracle(params, srv.run_key, r)
        assert trees_bitwise_equal(field_sum, plain), f"round {r}"
        params = rf(params, srv.run_key, r)


@pytest.mark.slow  # full server battery; the tiny tier-1 round pins the path
def test_fedavg_fused_secagg_bit_exact(task_and_clients):
    from ddl25spring_tpu.fl import FedAvgServer

    task, clients = task_and_clients
    sa = _battery_secagg(clients)
    srv = FedAvgServer(task, 0.05, 8, clients, 0.5, 1, 3, secagg=sa,
                       secagg_impl="fused",
                       fault_plan=FaultPlan.parse(DROP_PLAN))
    _assert_fused_bit_exact(srv, nr_rounds=4)
    assert (sa.stats["recovered_pair_keys"]
            + sa.stats["recovered_self_seeds"]) > 0
    assert sa.stats["unmask_failures"] == 0


@pytest.mark.slow  # full server battery; the tiny tier-1 round pins the path
def test_fedsgd_gradient_fused_secagg_bit_exact(task_and_clients):
    from ddl25spring_tpu.fl import FedSgdGradientServer

    task, clients = task_and_clients
    sa = _battery_secagg(clients)
    srv = FedSgdGradientServer(task, 0.05, clients, 0.5, 3, secagg=sa,
                               secagg_impl="fused",
                               fault_plan=FaultPlan.parse(DROP_PLAN))
    _assert_fused_bit_exact(srv)


@pytest.mark.slow  # full server battery; the tiny tier-1 round pins the path
def test_fedsgd_weight_fused_secagg_bit_exact(task_and_clients):
    from ddl25spring_tpu.fl import FedSgdWeightServer

    task, clients = task_and_clients
    sa = _battery_secagg(clients)
    srv = FedSgdWeightServer(task, 0.05, clients, 0.5, 3, secagg=sa,
                             secagg_impl="fused",
                             fault_plan=FaultPlan.parse(DROP_PLAN))
    _assert_fused_bit_exact(srv)


@pytest.mark.slow  # full server battery; the tiny tier-1 round pins the path
def test_fedopt_fused_secagg_bit_exact(task_and_clients):
    from ddl25spring_tpu.fl import FedOptServer

    task, clients = task_and_clients
    sa = _battery_secagg(clients)
    srv = FedOptServer(task, 0.05, 8, clients, 0.5, 1, 3,
                       server_optimizer="adam", server_lr=0.01, secagg=sa,
                       secagg_impl="fused",
                       fault_plan=FaultPlan.parse(DROP_PLAN))
    _assert_fused_bit_exact(srv)


@pytest.mark.slow  # full server battery; the tiny tier-1 round pins the path
def test_fedbuff_fused_secagg_bit_exact(task_and_clients):
    from ddl25spring_tpu.fl.fedbuff import FedBuffServer

    task, clients = task_and_clients
    sa = _battery_secagg(clients)
    srv = FedBuffServer(task, 0.05, 8, clients, 0.5, 1, 3,
                        staleness_window=3, secagg=sa,
                        secagg_impl="fused",
                        fault_plan=FaultPlan.parse(DROP_PLAN))
    rf = srv.round_fn
    assert rf.secagg_fused is True
    h = srv.params
    for r in range(3):
        field_sum, plain, _ = rf.secagg_oracle(h, srv.run_key, r)
        assert trees_bitwise_equal(field_sum, plain), f"tick {r}"
        h = rf(h, srv.run_key, r)
    assert sa.stats["rounds"] == 3


@pytest.mark.slow  # full server battery; the tiny tier-1 round pins the path
def test_fedavg_fused_grouped_secagg_bit_exact(task_and_clients):
    from ddl25spring_tpu.fl import FedAvgServer

    task, clients = task_and_clients
    sa = _battery_secagg(clients, nr_groups=2)
    srv = FedAvgServer(task, 0.05, 8, clients, 0.5, 1, 3, secagg=sa,
                       secagg_impl="fused",
                       fault_plan=FaultPlan.parse(DROP_PLAN))
    _assert_fused_bit_exact(srv)


# --------------------------------------------------------------------------
# compiled-kernel parity (TPU only; interpret mode covers CPU above)
# --------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.skipif(not ON_TPU, reason="compiled Pallas parity needs a TPU")
def test_pairwise_pallas_compiled_matches_gram_tpu():
    mat = _rand(256, 8192, jnp.float32)
    ref = pairwise.pairwise_sq_dists(mat, impl="gram")
    got = pairwise.pairwise_sq_dists(mat, impl="pallas", interpret=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=5e-2)
    # decision level must be exact even where float round-off isn't
    stacked = _outlier_stack(64)
    assert trees_bitwise_equal(
        make_krum(8, nr_selected=4, pairwise_impl="pallas")(stacked),
        make_krum(8, nr_selected=4, pairwise_impl="gram")(stacked),
    )


@pytest.mark.slow
@pytest.mark.skipif(not ON_TPU, reason="compiled Pallas parity needs a TPU")
def test_fused_masked_sums_compiled_matches_xla_tpu():
    msgs, spec, gids, live, surv, omega_u = _fused_case()
    fused = sa_kernels.fused_masked_sums(
        msgs, spec, 5, gids, live, surv, omega_u, 1, interpret=False
    )
    ref = _xla_masked_sums(msgs, spec, 5, gids, live, surv, omega_u, 1)
    assert trees_bitwise_equal(fused, ref)
