"""Fleet routing policy: pure host code, deliberately jax-free.

The router (``serving_fleet.router``) decides WHERE a request goes; the
replicas decide WHETHER it is admitted.  Everything the decision needs is
already host state on the batcher (queue depth, free slots, the chunk-time
EWMA, the shared-prefix tokens), so the policy is plain Python over
:class:`ReplicaSnapshot` values — unit-testable without a model, a mesh,
or even jax in the process (tests/test_serving_fleet.py guards that).

Ranking order (ties broken by the next key, then by replica index so the
routing trace is deterministic):

1. **Breaker state** — ``open`` replicas are excluded from the ranking
   entirely (no placements while the circuit is open); ``suspect``
   replicas are demoted behind every healthy/half-open one, whatever
   their affinity or load (``serving_fleet.health``).
2. **SLO feasibility** — replicas whose estimated admission wait already
   exceeds their SLO would reject; they go last, whatever their affinity.
3. **Canary preference** — a replica flagged as a rollout canary
   (``FleetRouter.mark_canary``) ranks FIRST among the feasible,
   non-suspect ones: the canary window is short and a canary that
   receives no traffic proves nothing, so the router deliberately
   steers placements at it while the burn gates watch.  A rejecting or
   breaker-open canary still re-routes/excludes as usual, so the
   preference never drops a request.
4. **Tenant affinity** — a replica whose adapter pool already holds the
   request's tenant adapter decodes it without a miss; a miss moves the
   factor bytes host→device AND may evict another tenant's adapter, so
   it outranks prefix affinity (whose miss merely recomputes prefill).
   Null-adapter traffic (``adapter_id=0``) ties on this key everywhere —
   the base-model ranking is unchanged.
5. **Prefix affinity** — a replica that already holds the request's
   prefix pages (ctor ``prefix_tokens``) or served the same prompt head
   recently skips prefill work and reuses warm KV pages.
6. **Least load** — fewest queued + active requests.
7. **SLO slack** — at equal load, the replica with the most headroom.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["ReplicaSnapshot", "rank_replicas", "snapshot_replica"]


@dataclass(frozen=True)
class ReplicaSnapshot:
    """One replica's routing-relevant state at decision time.

    ``est_wait_s`` is the replica's own admission-wait estimate (queue
    drain + pool deficit); ``slo_slack_s`` is its SLO minus that wait,
    ``inf`` when the replica has no admission SLO (it never rejects on
    wait, so it is always feasible).
    """

    index: int
    queue_len: int
    active: int
    free_slots: int
    prefix_hit: bool = False
    tenant_hit: bool = False        # tenant's adapter resident here
    est_wait_s: float = 0.0
    slo_slack_s: float = float("inf")
    health_state: str = "healthy"   # serving_fleet.health breaker state
    canary: bool = False            # rollout canary: prefer for traffic

    @property
    def load(self) -> int:
        return self.queue_len + self.active


def rank_replicas(snapshots) -> list[int]:
    """Replica indices in routing-preference order (best first).

    ``open``-breaker replicas are dropped, not just demoted — placing
    on them would feed a replica already proven unhealthy.  ``suspect``
    replicas stay eligible (the breaker may be wrong) but behind every
    non-suspect one.
    """
    return [s.index for s in sorted(
        (s for s in snapshots if s.health_state != "open"),
        key=lambda s: (
            1 if s.health_state == "suspect" else 0,  # demote suspects
            1 if s.slo_slack_s <= 0.0 else 0,   # would reject: last
            0 if s.canary else 1,                # steer at the canary
            0 if s.tenant_hit else 1,            # resident adapter first
            0 if s.prefix_hit else 1,            # warm prefix first
            s.load,                              # then least loaded
            -s.slo_slack_s,                      # then most headroom
            s.index,                             # deterministic trace
        ),
    )]


def snapshot_replica(index: int, batcher, prompt, budget: int, *,
                     affinity_hit: bool = False,
                     adapter_id: int = 0,
                     health_state: str = "healthy",
                     canary: bool = False,
                     capacity_model=None) -> ReplicaSnapshot:
    """Build a snapshot from a live batcher by reading HOST state only
    (queue, slots, EWMAs) — no device round trip, no jax import.

    ``affinity_hit`` is the router's own recency signal (same prompt head
    routed here before); it ORs with the replica's ctor-level shared
    prefix, which is the stronger signal (precomputed pages, prefill
    skipped entirely).

    ``capacity_model`` (an ``obs.CapacityModel``-shaped object, duck
    typed so this module stays import-free) refines ``est_wait_s`` for
    replicas that have not decoded yet: the batcher's own estimate rides
    its chunk-time EWMA, which is a placeholder until the first chunk,
    so a calibrated prediction replaces it on cold replicas only.
    """
    hit = bool(affinity_hit)
    # tenant affinity: duck-typed adapter_resident so non-adapter
    # batchers (and fakes) rank exactly as before; a NON-resident tenant
    # on an adapter batcher is an honest miss (tenant_hit False), while
    # adapter_id=0 always hits — null traffic ties everywhere
    tenant_hit = False
    if adapter_id:
        probe = getattr(batcher, "adapter_resident", None)
        tenant_hit = bool(probe(adapter_id)) if callable(probe) else False
    ptoks = getattr(batcher, "_prefix_tokens", None)
    if ptoks is not None:
        n = len(ptoks)
        p = list(prompt)
        hit = hit or (len(p) > n
                      and tuple(int(t) for t in p[:n]) == tuple(ptoks))
    queue_len = len(getattr(batcher, "_queue", ()))
    slots = getattr(batcher, "slots", ())
    active = sum(1 for sl in slots if not sl.free)
    slack = float("inf")
    est_wait = 0.0
    slo = getattr(batcher, "slo_deadline_s", None)
    estimate = getattr(batcher, "_admission_wait_estimate", None)
    if estimate is not None and budget > 0:
        est_wait, _bound = estimate(budget)
        if capacity_model is not None and not getattr(batcher, "_chunk_s",
                                                      0.0):
            mb = max(1, int(getattr(batcher, "max_batch", 1)))
            w = capacity_model.predict_wait_s(
                queue_len, mb, occupancy=mb, batch=mb,
                chunk=getattr(batcher, "decode_chunk", 0) or 0)
            if w is not None:
                est_wait = float(w)
        if slo is not None:
            slack = float(slo) - est_wait
    return ReplicaSnapshot(
        index=index, queue_len=queue_len, active=active,
        free_slots=len(slots) - active, prefix_hit=hit,
        tenant_hit=tenant_hit, est_wait_s=est_wait, slo_slack_s=slack,
        health_state=health_state, canary=canary,
    )
