"""Multi-host (multi-process) mesh initialisation over ICI + DCN.

The reference's multi-node story is ``torch.distributed.init_process_group``
with a TCP rendezvous via ``MASTER_ADDR``/``MASTER_PORT`` env vars
(lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:12-15) and gloo collectives.
The TPU-native equivalent is JAX's coordination service: every host runs the
SAME SPMD program, ``jax.distributed.initialize`` performs the rendezvous,
and after it ``jax.devices()`` spans the whole pod slice — the collectives
the mesh programs in this package already use (psum/ppermute/all_gather)
then ride ICI within a slice and DCN across slices, chosen by XLA from the
mesh axis layout.  No per-rank scripts, no send/recv matching, no port
bookkeeping beyond the coordinator address.

Axis-layout rule of thumb (the scaling-book recipe): put the axes with the
heaviest collectives (TP/SP, then DP grad reduction) on ICI — the innermost
mesh axes over devices within a host/slice — and the lightest (PP stage
hand-off, or pure DP across pods) on DCN, the outermost axis over hosts.
``make_multihost_mesh`` encodes exactly that: its first axis spans hosts.
"""

from __future__ import annotations

import os

import jax


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join this process to a multi-host JAX cluster; returns True if a
    multi-process runtime was initialised, False for the single-host no-op.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``).  With no config at all this
    returns False and leaves jax untouched, so every entry point can call it
    unconditionally — the reference's MASTER_ADDR plumbing collapses into
    one optional call.  A PARTIAL config raises: silently falling back to
    single-host would make N processes train independently (duplicated
    work, divergent params) with no error in sight.  On managed TPU pods
    (GKE/Cloud TPU VMs), where jax auto-detects the topology, call
    ``jax.distributed.initialize()`` directly instead.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    num_str = os.environ.get("JAX_NUM_PROCESSES")
    if num_processes is None and num_str:
        num_processes = int(num_str)
    pid_str = os.environ.get("JAX_PROCESS_ID")
    if process_id is None and pid_str:
        process_id = int(pid_str)

    provided = {
        "coordinator_address": coordinator_address,
        "num_processes": num_processes,
        "process_id": process_id,
    }
    missing = [name for name, v in provided.items() if v is None]
    if len(missing) == 3:
        return False  # single host; nothing to rendezvous
    if missing:
        raise ValueError(
            f"partial multi-host config: {missing} unset while "
            f"{[n for n in provided if n not in missing]} set — refusing to "
            "fall back to single-host (N processes would train "
            "independently); set all three or none"
        )

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    # tag this rank into the trace context: every span this process emits
    # now carries process=<rank>, which is what keeps per-rank JSONL on
    # distinct tracks when obs/export.py merges them into one timeline
    from ..obs import trace as obs_trace

    obs_trace.set_process_index(jax.process_index())
    return True


def make_multihost_mesh(
    ici_axes: dict[str, int] | None = None,
    dcn_axis: str = "dcn",
    devices=None,
):
    """Mesh whose OUTERMOST axis spans processes/hosts (rides DCN) and whose
    inner axes subdivide each host's local devices (ride ICI).

    ``ici_axes`` maps inner axis names to sizes whose product must equal the
    local device count (default: one ``data`` axis over all local devices).
    On a single process this degenerates to a ``{dcn_axis: 1}`` outer axis,
    so programs written against the multi-host layout run unchanged on one
    host — the fake-mesh test harness exercises exactly that path.
    """
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    nr_processes = max(
        (getattr(d, "process_index", 0) for d in devices), default=0
    ) + 1
    local = len(devices) // nr_processes
    if nr_processes * local != len(devices):
        raise ValueError(
            f"{len(devices)} devices do not split evenly over "
            f"{nr_processes} processes"
        )
    ici_axes = dict(ici_axes) if ici_axes else {"data": local}
    ici_total = 1
    for size in ici_axes.values():
        ici_total *= size
    if ici_total != local:
        raise ValueError(
            f"ici axes {ici_axes} product {ici_total} != local device "
            f"count {local}"
        )
    shape = (nr_processes,) + tuple(ici_axes.values())
    names = (dcn_axis,) + tuple(ici_axes)
    if nr_processes > 1:
        # process_is_granule: the outer axis spans PROCESSES (hosts), as the
        # docstring promises — the default slice granularity would reject
        # multi-host single-slice pods (1 slice != nr_processes) and CPU
        # multi-process harnesses (no slice_index attribute at all)
        device_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1,) + shape[1:],  # per-axis local factor
            dcn_mesh_shape=(nr_processes,) + (1,) * len(ici_axes),
            devices=devices,
            process_is_granule=True,
        )
    else:
        import numpy as np

        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, names)
