"""Crash flight recorder: a bounded "black box" for the serving fleet.

When a replica crashes its in-memory state vanishes — the breaker
timeline says *that* it died, nothing says *what the fleet was doing*.
The :class:`FlightRecorder` keeps bounded rings of recent activity and
writes them to ``results/flightrec_*.json`` the moment something goes
wrong, so a postmortem always has the last N events even when the
process that produced them is gone.

Channels (each an independent ring of ``capacity`` records):

* ``events``     — every telemetry event, teed via the registry's
  event hook (``obs.core.add_event_hook``) regardless of sink;
* ``replica:<i>`` — the same events, routed by their ``replica`` field
  (breaker transitions, failures, req-trace phases on that replica);
* ``router``     — routing decisions the router records explicitly
  (placements, re-routes, failovers, orphan re-placements);
* ``samples``    — per-step last-values of the installed
  :class:`~ddl25spring_tpu.obs.timeseries.TimeSeriesRecorder` series
  (written by ``obs.record_samples``).

Dump triggers (checked on every teed event):

* ``fleet.replica_failed``                    -> ``replica_failed``
* ``fleet.breaker`` with ``to == "open"``     -> ``breaker_open``
* ``slo.burn`` with ``state == "burning"``    -> ``burn_alert``
* ``fleet.rollout_rolled_back``               -> ``rollout_rollback``

Each dump is one JSON file ``<prefix>_<n>_<reason>.json`` with the ring
contents, the trigger, a registry snapshot and any extra sources wired
in (``obs.install_flight`` adds the installed req-trace recorder's
summary) — ``tools/obs_postmortem.py`` merges it with trace/metrics
JSONL into a root-cause report.  Dump filenames are counter-sequenced,
never wall-clock-derived, so seeded chaos runs dump to stable names.

Stdlib-only and jax-import-free (``analysis/manifest.HOST_ONLY_MODULES``);
never imports the :mod:`ddl25spring_tpu.obs` package root — the registry
reaches it through the event hook and explicit ``telemetry=`` arguments.
"""

from __future__ import annotations

import itertools
import json
import time
from collections import deque
from pathlib import Path

__all__ = ["FlightRecorder"]

# event -> (reason, field predicate) for automatic dumps
_TRIGGERS = {
    "fleet.replica_failed": ("replica_failed", None),
    "fleet.breaker": ("breaker_open", ("to", "open")),
    "slo.burn": ("burn_alert", ("state", "burning")),
    "fleet.rollout_rolled_back": ("rollout_rollback", None),
}


class FlightRecorder:
    """Bounded rings of recent fleet activity, dumped on crashes.

    ``capacity`` bounds every channel independently; ``max_dumps``
    bounds files written per process (a crash loop must not fill the
    disk — suppressed dumps are counted, not written).  ``out_dir`` is
    where dumps land (default ``results/``, created lazily).
    """

    def __init__(self, capacity: int = 256, *, out_dir="results",
                 prefix: str = "flightrec", max_dumps: int = 16):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.out_dir = Path(out_dir)
        self.prefix = prefix
        self.max_dumps = max_dumps
        self._channels: dict = {}
        self._seq = itertools.count()
        self._dump_seq = itertools.count()
        self.dumps: list = []          # Paths written, in order
        self.suppressed = 0            # dumps skipped past max_dumps
        # name -> zero-arg callable returning a JSON-able payload,
        # invoked at dump time (obs.install_flight wires "reqtrace")
        self.extra_sources: dict = {}

    # -- rings -----------------------------------------------------------

    def channel(self, name: str) -> deque:
        q = self._channels.get(name)
        if q is None:
            q = self._channels[name] = deque(maxlen=self.capacity)
        return q

    def record(self, channel: str, kind: str, **fields) -> dict:
        """Append one record to ``channel``.  ``seq`` is a process-wide
        monotone counter, so merged channels re-interleave exactly."""
        rec = {"seq": next(self._seq), "kind": kind, **fields}
        self.channel(channel).append(rec)
        return rec

    # -- event hook (wired by obs.install_flight) ------------------------

    def on_event(self, telemetry, event: str, fields: dict) -> None:
        """Tee one telemetry event into the rings and dump when it is a
        trigger.  Called from ``Telemetry.event`` via the registry event
        hook; exceptions are swallowed there, but keep this cheap —
        every event pays it while a recorder is installed."""
        if event == "telemetry_summary":
            return                      # bulky, reconstructable from dump
        rec = {"seq": next(self._seq), "kind": event, **fields}
        self.channel("events").append(rec)
        r = fields.get("replica")
        if r is not None:
            self.channel(f"replica:{r}").append(rec)
        trig = _TRIGGERS.get(event)
        if trig is not None:
            reason, pred = trig
            if pred is None or fields.get(pred[0]) == pred[1]:
                self.dump(reason, telemetry=telemetry,
                          trigger={"event": event, **fields})

    # -- dumps -----------------------------------------------------------

    def dump(self, reason: str, *, telemetry=None, **context) -> Path | None:
        """Write the black box to ``<out_dir>/<prefix>_<n>_<reason>.json``
        and return the path (None when ``max_dumps`` suppressed it)."""
        if len(self.dumps) >= self.max_dumps:
            self.suppressed += 1
            return None
        n = next(self._dump_seq)
        payload = {
            "reason": reason,
            "dump_seq": n,
            "ts": round(time.time(), 3),
            "context": context,
            "channels": {name: list(q)
                         for name, q in sorted(self._channels.items())},
        }
        for name, fn in self.extra_sources.items():
            try:
                payload[name] = fn()
            except Exception as e:      # a dump must never take the
                payload[name] = {"error": repr(e)}  # program down with it
        if telemetry is not None:
            payload["summary"] = telemetry.snapshot()
            telemetry.counter("flightrec_dumps_total", reason=reason).inc()
        self.out_dir.mkdir(parents=True, exist_ok=True)
        path = self.out_dir / f"{self.prefix}_{n:03d}_{reason}.json"
        path.write_text(json.dumps(payload, indent=1, default=repr))
        self.dumps.append(path)
        return path

    def describe(self) -> dict:
        return {"channels": {n: len(q)
                             for n, q in sorted(self._channels.items())},
                "dumps": [str(p) for p in self.dumps],
                "suppressed": self.suppressed}
