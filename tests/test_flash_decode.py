"""Flash-decode kernel oracles (ops/flash_decode.py).

The kernel must match the XLA decode path (models/llama.py einsum over the
full cache) exactly — including GQA grouping and ragged left-pad masking —
and greedy generation through it must be bit-identical to the default
decode implementation.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ddl25spring_tpu.models import Llama, LlamaConfig, generate
from ddl25spring_tpu.ops.flash_decode import flash_decode_attention


def _xla_decode(q, ck, cv, pos, pad):
    """The reference math: full-cache grouped einsum + mask (llama.py)."""
    B, Hq, hd = q.shape
    _, S, Hkv, _ = ck.shape
    g = Hq // Hkv
    qg = q.reshape(B, Hkv, g, hd)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, ck).astype(jnp.float32) * scale
    valid = (jnp.arange(S)[None, :] <= pos) & (
        jnp.arange(S)[None, :] >= pad[:, None]
    )  # (B, S)
    scores = jnp.where(valid[:, None, None], scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", att, cv)
    return out.reshape(B, Hq, hd)


def test_flash_decode_matches_xla_einsum():
    B, S, Hq, Hkv, hd = 3, 64, 4, 2, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, Hq, hd))
    ck = jax.random.normal(ks[1], (B, S, Hkv, hd))
    cv = jax.random.normal(ks[2], (B, S, Hkv, hd))
    pad = jnp.asarray([0, 3, 10])
    for pos in (12, 37, S - 1):
        got = flash_decode_attention(q, ck, cv, pos, pad)
        want = _xla_decode(q, ck, cv, pos, pad)
        np.testing.assert_allclose(got, want, atol=1e-5, err_msg=f"pos={pos}")
    # pad=None == zeros
    np.testing.assert_allclose(
        flash_decode_attention(q, ck, cv, 20, None),
        _xla_decode(q, ck, cv, 20, jnp.zeros(B, jnp.int32)), atol=1e-5,
    )


def test_generation_with_flash_decode_matches_default():
    """Greedy generation with decode_impl='flash-decode' matches the XLA
    decode path token-for-token — plain and ragged batches.

    Exact equality is a property of THIS pinned test environment (CPU,
    float32, fixed seeds — conftest forces it): the two paths differ at the
    last-ulp level (online matmul-then-normalise vs softmax-then-matmul),
    so near-tied argmaxes could flip on other platforms/dtypes.  The
    platform-independent correctness oracle is the atol-bounded kernel
    test above; this test pins the end-to-end WIRING (config plumbing,
    cache handoff, pad threading), where any real bug would diverge far
    beyond a tied argmax."""
    cfg = LlamaConfig(vocab_size=32, dmodel=32, nr_heads=4, nr_kv_heads=2,
                      nr_layers=2, ctx_size=24)
    fcfg = dataclasses.replace(cfg, decode_impl="flash-decode")
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 1, 32)
    params = Llama(cfg).init(jax.random.key(2), prompt,
                             positions=jnp.arange(5))
    np.testing.assert_array_equal(
        np.asarray(generate(cfg, params, prompt, 8)),
        np.asarray(generate(fcfg, params, prompt, 8)),
    )
    lengths = jnp.asarray([2, 5])
    np.testing.assert_array_equal(
        np.asarray(generate(cfg, params, prompt, 6, prompt_lengths=lengths)),
        np.asarray(generate(fcfg, params, prompt, 6, prompt_lengths=lengths)),
    )


def test_flash_decode_head_grouping_matrix():
    """Kernel vs einsum across the head-grouping spectrum: MHA (g=1),
    GQA (g=2), MQA (one KV head serving all queries)."""
    B, S, hd = 2, 32, 8
    ks = jax.random.split(jax.random.key(7), 3)
    for Hq, Hkv in ((4, 4), (4, 2), (4, 1)):
        q = jax.random.normal(ks[0], (B, Hq, hd))
        ck = jax.random.normal(ks[1], (B, S, Hkv, hd))
        cv = jax.random.normal(ks[2], (B, S, Hkv, hd))
        pad = jnp.asarray([0, 5])
        np.testing.assert_allclose(
            flash_decode_attention(q, ck, cv, 17, pad),
            _xla_decode(q, ck, cv, 17, pad),
            atol=1e-5, err_msg=f"Hq={Hq} Hkv={Hkv}",
        )


def test_flash_decode_per_row_positions():
    """(B,) pos vector: each row's live prefix, DMA clamp and mask use its
    own slot (the speculative-decoding layout where rows diverge)."""
    B, S, Hq, Hkv, hd = 4, 96, 4, 2, 16
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (B, Hq, hd))
    ck = jax.random.normal(ks[1], (B, S, Hkv, hd))
    cv = jax.random.normal(ks[2], (B, S, Hkv, hd))
    pos = jnp.asarray([5, 50, 95, 17], jnp.int32)
    pad = jnp.asarray([0, 3, 0, 2], jnp.int32)

    got = flash_decode_attention(q, ck, cv, pos, pad)
    # per-row oracle: full-cache einsum with a per-row visibility window
    g = Hq // Hkv
    qg = q.reshape(B, Hkv, g, hd)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, ck).astype(jnp.float32)
    scores = scores * scale
    valid = (jnp.arange(S)[None, :] <= pos[:, None]) & (
        jnp.arange(S)[None, :] >= pad[:, None]
    )
    scores = jnp.where(valid[:, None, None], scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    want = jnp.einsum("bkgs,bskd->bkgd", att, cv).reshape(B, Hq, hd)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def _quant_ref(x):
    """models/llama.py's per-(token, head) absmax int8 quantization."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    qv = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                  -127, 127).astype(jnp.int8)
    return qv, scale.astype(jnp.float32)


def test_flash_decode_int8_matches_dequantized_einsum():
    """int8-cache kernel: streaming quantized blocks + in-VMEM dequant must
    equal the XLA path's dequantize-then-einsum on the same quantized
    cache (same _Deq math — value * scale in the compute dtype), across
    GQA groupings and ragged pads."""
    B, S, hd = 2, 64, 8
    ks = jax.random.split(jax.random.key(11), 3)
    for Hq, Hkv in ((4, 4), (4, 2), (4, 1)):
        q = jax.random.normal(ks[0], (B, Hq, hd))
        ck = jax.random.normal(ks[1], (B, S, Hkv, hd))
        cv = jax.random.normal(ks[2], (B, S, Hkv, hd))
        kq, kscale = _quant_ref(ck)
        vq, vscale = _quant_ref(cv)
        pad = jnp.asarray([0, 4])
        for pos in (9, S - 1):
            got = flash_decode_attention(
                q, kq, vq, pos, pad,
                cache_k_scale=kscale, cache_v_scale=vscale,
            )
            want = _xla_decode(
                q, kq.astype(q.dtype) * kscale[..., None].astype(q.dtype),
                vq.astype(q.dtype) * vscale[..., None].astype(q.dtype),
                pos, pad,
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5,
                err_msg=f"Hq={Hq} Hkv={Hkv} pos={pos}",
            )


def test_generation_int8_flash_matches_int8_xla():
    """End-to-end: kv_cache_int8 generation through the flash-decode
    kernel must emit the same tokens as kv_cache_int8 through the XLA
    einsum path (same quantized cache, same dequant math — the impl is
    not allowed to change the numbers)."""
    cfg = LlamaConfig(vocab_size=32, dmodel=32, nr_heads=4, nr_kv_heads=2,
                      nr_layers=2, ctx_size=24, kv_cache_int8=True)
    fcfg = dataclasses.replace(cfg, decode_impl="flash-decode")
    xcfg = dataclasses.replace(cfg, decode_impl="xla")
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 1, 32)
    params = Llama(dataclasses.replace(cfg, kv_cache_int8=False)).init(
        jax.random.key(2), prompt, positions=jnp.arange(5)
    )
    np.testing.assert_array_equal(
        np.asarray(generate(xcfg, params, prompt, 8)),
        np.asarray(generate(fcfg, params, prompt, 8)),
    )


def test_decode_impl_auto_resolution():
    """'auto' (the default since the round-4 hardware validation) resolves
    by backend and eligibility; explicit impls pass through untouched."""
    import dataclasses

    import jax

    from ddl25spring_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(decode=True)
    assert cfg.decode_impl == "auto"
    # CPU test backend -> xla; on TPU auto goes all the way to the fused
    # serving inner step (ops/fused_decode_step.py)
    assert cfg.resolved_decode_impl() == (
        "fused" if jax.default_backend() == "tpu" else "xla"
    )
    # ineligible shapes resolve to xla even on TPU
    assert dataclasses.replace(
        cfg, ctx_size=256, decode_seq_shards=2
    ).resolved_decode_impl() == "xla"
    # int8 caches are ELIGIBLE since round 5 (the kernel dequantizes
    # in-stream): auto treats them like any other cache
    assert dataclasses.replace(
        cfg, kv_cache_int8=True
    ).resolved_decode_impl(backend="tpu") == "fused"
    # explicit settings are never overridden
    assert dataclasses.replace(
        cfg, decode_impl="flash-decode"
    ).resolved_decode_impl() == "flash-decode"
    assert dataclasses.replace(
        cfg, decode_impl="xla"
    ).resolved_decode_impl() == "xla"
    # 'fused' is a serving-loop fusion, not an attention impl: the cache
    # read under it rides flash-decode on TPU and the einsum elsewhere
    fcfg = dataclasses.replace(cfg, decode_impl="fused")
    assert fcfg.resolved_decode_impl() == "fused"
    assert fcfg.decode_attention_impl(backend="tpu") == "flash-decode"
    assert fcfg.decode_attention_impl(backend="cpu") == "xla"
    assert dataclasses.replace(
        cfg, decode_impl="flash-decode"
    ).decode_attention_impl(backend="cpu") == "flash-decode"


def _xla_decode_prefix(q, ck, cv, pos, pad, prefix_len):
    """Reference mask with a shared prefix: garbage window sits at
    [prefix_len, prefix_len + pad); prefix slots below it are real."""
    B, Hq, hd = q.shape
    _, S, Hkv, _ = ck.shape
    g = Hq // Hkv
    qg = q.reshape(B, Hkv, g, hd)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, ck).astype(jnp.float32) * scale
    slot = jnp.arange(S)[None, :]
    live = slot <= pos  # scalar pos; per-row cases loop rows in the caller
    real = (slot < prefix_len) | (slot >= prefix_len + pad[:, None])
    scores = jnp.where((live & real)[:, None, None], scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", att, cv)
    return out.reshape(B, Hq, hd)


def test_flash_decode_prefix_mask():
    """prefix_len shifts the garbage window: slots [0, P) stay REAL,
    [P, P + pad) are hidden — scalar and per-row positions, fp and int8
    cache."""
    B, S, Hq, Hkv, hd, P = 3, 64, 4, 2, 8, 9
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (B, Hq, hd))
    ck = jax.random.normal(ks[1], (B, S, Hkv, hd))
    cv = jax.random.normal(ks[2], (B, S, Hkv, hd))
    pad = jnp.asarray([0, 2, 5])
    for pos in (P + 6, S - 1):
        got = flash_decode_attention(q, ck, cv, pos, pad, prefix_len=P)
        want = _xla_decode_prefix(q, ck, cv, pos, pad, P)
        np.testing.assert_allclose(got, want, atol=1e-5, err_msg=f"pos={pos}")
    # per-row positions (speculative rows diverge)
    posv = jnp.asarray([P + 6, P + 11, S - 1])
    got = flash_decode_attention(q, ck, cv, posv, pad, prefix_len=P)
    want = np.stack([
        np.asarray(_xla_decode_prefix(q[b:b + 1], ck[b:b + 1], cv[b:b + 1],
                                      int(posv[b]), pad[b:b + 1], P))[0]
        for b in range(B)
    ])
    np.testing.assert_allclose(got, want, atol=1e-5)
    # prefix_len=0 keeps the pre-existing no-prefix program exactly
    np.testing.assert_allclose(
        flash_decode_attention(q, ck, cv, 20, pad, prefix_len=0),
        flash_decode_attention(q, ck, cv, 20, pad), atol=0,
    )
    # int8 cache: the quantized kernel shares _valid_mask — dequantized
    # operands through the prefix-shifted mask must match the einsum
    # reference on the same dequantized values
    def quant(blk):
        amax = jnp.max(jnp.abs(blk), axis=-1)
        s = jnp.maximum(amax, 1e-8) / 127.0
        qv = jnp.clip(jnp.round(blk / s[..., None]), -127, 127)
        return qv.astype(jnp.int8), s.astype(jnp.float32)

    kq, ks8 = quant(ck)
    vq, vs8 = quant(cv)
    got = flash_decode_attention(q, kq, vq, S - 1, pad,
                                 cache_k_scale=ks8, cache_v_scale=vs8,
                                 prefix_len=P)
    want = _xla_decode_prefix(
        q, kq.astype(q.dtype) * ks8[..., None],
        vq.astype(q.dtype) * vs8[..., None], S - 1, pad, P,
    )
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_generation_prefix_with_flash_decode_matches_xla():
    """End-to-end: generate() over a cached prefix with
    decode_impl='flash-decode' is bit-identical to the einsum path —
    plain AND ragged (the composition the round-5 kernel mask unlocks) —
    and speculative decoding over a prefix with a flash-decode draft
    still reproduces the dense path's output."""
    from ddl25spring_tpu.models.generate import precompute_prefix
    from ddl25spring_tpu.models.speculative import speculative_generate

    base = LlamaConfig(vocab_size=48, dmodel=32, nr_heads=4, nr_kv_heads=2,
                       nr_layers=2, ctx_size=96, decode_impl="xla")
    flash = dataclasses.replace(base, decode_impl="flash-decode")
    toks = jnp.zeros((2, 5), jnp.int32)
    params = Llama(base).init(jax.random.key(0), toks,
                              positions=jnp.arange(5))
    pref = jax.random.randint(jax.random.key(30), (11,), 1, 48)
    t_pref = precompute_prefix(base, params, pref)

    prompt = jax.random.randint(jax.random.key(31), (3, 6), 1, 48)
    lengths = jnp.asarray([2, 6, 4])
    for kw in (dict(), dict(prompt_lengths=lengths)):
        want = generate(base, params, prompt, 12, prefix=t_pref, **kw)
        got = generate(flash, params, prompt, 12, prefix=t_pref, **kw)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    dcfg = dataclasses.replace(base, dmodel=16, nr_heads=2, nr_kv_heads=2,
                               nr_layers=1)
    dflash = dataclasses.replace(dcfg, decode_impl="flash-decode")
    dparams = Llama(dcfg).init(jax.random.key(1), toks,
                               positions=jnp.arange(5))
    d_pref = precompute_prefix(dcfg, dparams, pref)
    want, _ = speculative_generate(base, params, dcfg, dparams, prompt, 10,
                                   gamma=3, prefix=(t_pref, d_pref))
    got, _ = speculative_generate(base, params, dflash, dparams, prompt, 10,
                                  gamma=3, prefix=(t_pref, d_pref))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
