"""Process-global telemetry: no-op by default, one call to turn on.

Importing this package never imports jax (guarded by
``tests/test_obs.py``), so CPU-only CI and host tools can use it freely.
Telemetry is OFF until :func:`enable` is called; every module-level helper
(:func:`span`, :func:`inc`, :func:`observe`, :func:`set_gauge`,
:func:`event`) short-circuits on a single ``is None`` check when disabled —
no allocation, no locking, no event writes — so instrumented library code
pays nothing in the default configuration.

Typical use::

    from ddl25spring_tpu import obs

    obs.enable("results/telemetry.jsonl")       # JSONL sink via MetricsLogger
    ...                                          # instrumented code runs
    obs.flush()                                  # one telemetry_summary event
    print(obs.render_prom())                     # Prometheus text exposition

Library code instruments unconditionally::

    with obs.span("serving.decode", chunk=k) as sp:
        out = dispatch(...)          # sp.fence(out) to also time the device

See ``docs/OBSERVABILITY.md`` for the event schema and
``tools/obs_report.py`` for rendering the JSONL into a report.
"""

from __future__ import annotations

from .core import (DEFAULT_BUCKETS, NULL_SPAN, Counter, Gauge, Histogram,
                   Telemetry)

__all__ = [
    "Telemetry", "Counter", "Gauge", "Histogram", "DEFAULT_BUCKETS",
    "enable", "disable", "enabled", "get",
    "span", "inc", "observe", "set_gauge", "event", "flush", "render_prom",
]

_T: Telemetry | None = None


def enable(jsonl_path=None, *, sink=None, echo: bool = False) -> Telemetry:
    """Turn telemetry on process-wide and return the registry.

    ``jsonl_path`` opens a ``MetricsLogger`` JSONL sink there (this is the
    one place obs touches ``utils.logging``, lazily — that import pulls
    jax, which any process calling ``enable`` has anyway); ``sink`` passes
    an explicit ``log(event, **fields)`` object instead; neither means
    instruments aggregate in-process only (no event stream).  Calling
    ``enable`` again replaces the registry (fresh instruments)."""
    global _T
    if sink is None and jsonl_path is not None:
        from ..utils.logging import MetricsLogger
        sink = MetricsLogger(jsonl_path, echo=echo)
    _T = Telemetry(sink=sink)
    return _T


def disable():
    """Turn telemetry off: helpers return to their no-op fast path."""
    global _T
    _T = None


def enabled() -> bool:
    return _T is not None


def get() -> Telemetry | None:
    """The active registry, or None when disabled — for code that needs
    direct instrument access (``obs.get().render_prom()``...)."""
    return _T


def span(name: str, **fields):
    """Timing context manager (see :meth:`Telemetry.span`); a shared no-op
    when disabled."""
    t = _T
    return NULL_SPAN if t is None else t.span(name, **fields)


def inc(name: str, n=1, **labels):
    t = _T
    if t is not None:
        t.counter(name, **labels).inc(n)


def observe(name: str, value, **labels):
    t = _T
    if t is not None:
        t.histogram(name, **labels).observe(value)


def set_gauge(name: str, value, **labels):
    t = _T
    if t is not None:
        t.gauge(name, **labels).set(value)


def event(name: str, **fields):
    t = _T
    if t is not None:
        t.event(name, **fields)


def flush():
    """Emit the aggregate snapshot as one ``telemetry_summary`` event."""
    t = _T
    if t is not None:
        t.flush()


def render_prom() -> str:
    t = _T
    return "" if t is None else t.render_prom()
