"""trace-hygiene pass: host-Python constructs inside traced code.

Entry points are found statically — functions decorated with (or passed
to) ``jax.jit`` / ``pjit`` / ``pl.pallas_call`` / ``shard_map`` — and the
pass walks the static call graph from them (same-module defs at any
nesting depth, plus ``from .x import f`` edges).  Inside reachable
functions a lightweight intra-function taint marks values derived from
the function's array parameters and from ``jax.*`` calls, then flags:

- ``TRC001/TRC002`` — Python ``if``/``while``/``assert`` on a traced
  value (concretization error or silent trace-time constant);
- ``TRC003`` — ``.item()``/``.tolist()``/``float()``/``int()``/
  ``bool()`` on a traced value (host sync / ConcretizationTypeError);
- ``TRC004`` — ``np.*`` applied to a traced value (silently falls back
  to host numpy or fails, either way breaks the trace);
- ``TRC005`` — ``print`` in traced code (runs at trace time only; use
  ``jax.debug.print``);
- ``TRC006/TRC007`` — ``time.*`` / ``random.*``/``np.random`` in traced
  code (evaluated once at trace time, then baked in — the retrace
  lottery);
- ``TRC008`` — ``lax.ppermute`` inside a ``shard_map`` body naming an
  axis the call site's specs never mention (a typo'd axis name fails
  at run time with an opaque unbound-axis error — or silently permutes
  over the wrong mesh dimension when the name happens to exist).  Only
  checked when the ``shard_map`` call spells its axis names as string
  literals inside ``P(...)``/``PartitionSpec(...)`` specs AND the
  ``ppermute`` names its axis as a string literal; specs or axis names
  built from variables (the repo's own ring primitives thread ``axis``
  through as a parameter) make the check abstain rather than guess.

Heuristics, stated plainly:

- parameters are traced unless they are ``self``/``cls``, named in
  ``static_argnames``/``static_argnums``, carry a literal non-None
  default, or are annotated with a clearly non-array type (``int``,
  ``float``, ``LlamaConfig``, ...) — only annotations mentioning
  ``Array``/``ndarray``/``Any``/pytree-ish names stay traced;
- when a reached function *calls* another in-project function, the
  callee's parameters matching call arguments that are untainted at the
  call site are treated static (first call site to reach a function
  wins);
- ``.shape``/``.dtype``/``len()``/``jnp.issubdtype``/``is``-comparisons
  are static under tracing and un-taint;
- a tainted ``if`` whose body is only ``raise`` is a validation guard —
  failing loudly at trace time is its purpose — and is not flagged,
  and expressions inside ``raise`` statements are never flagged;
- an ``isinstance(x, ...)`` test un-taints ``x`` in both branches (the
  ``jax.core.Tracer`` host-guard idiom);
- concretizations inside a ``try`` whose handler catches a
  ``Tracer*``/``Concretization*`` error are explicitly handled and not
  flagged;
- functions passed to ``*_callback`` escape to the host and are not
  followed.

Residual false positives are baselined with a justification rather than
special-cased here.
"""

from __future__ import annotations

import ast

from .core import Finding, ProjectIndex, dotted_name, terminal_name

PASS_ID = "trace-hygiene"

TRACE_ENTRY = {"jit", "pjit", "pallas_call", "shard_map"}
UNTAINT_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "aval",
                 "weak_type", "itemsize", "nbytes"}
SAFE_CALLS = {"len", "isinstance", "type", "repr", "hash", "getattr",
              "hasattr", "callable", "id", "str", "format"}
CAST_CALLS = {"float", "int", "bool", "complex"}
ITEM_METHODS = {"item", "tolist"}
CALLBACK_CALLS = {"pure_callback", "io_callback", "callback",
                  "debug_callback"}
EXTERNAL_ROOTS = ("jax", "numpy", "time", "random", "datetime", "os")
# jax calls whose results are static metadata, not tracers
JAXY_STATIC = {"jax.numpy.issubdtype", "jax.dtypes.issubdtype",
               "jax.numpy.result_type", "jax.numpy.ndim",
               "jax.numpy.shape", "jax.eval_shape",
               "jax.tree_util.tree_structure",
               "jax.experimental.pallas.cdiv"}
# annotation tokens that mean "this parameter really is an array/pytree"
ARRAYISH_ANN = {"Array", "ndarray", "ArrayLike", "array", "Any",
                "PyTree", "object"}
TRACER_EXC_MARKERS = ("Tracer", "Concretization")


class ModCtx:
    """Per-module resolution tables for the call-graph walk."""

    def __init__(self, mi, idx: ProjectIndex):
        self.mi = mi
        self.idx = idx
        self.alias: dict[str, str] = {}        # local name -> dotted ext
        self.funcimports: dict[str, tuple[str, str]] = {}  # name->(mod,fn)
        self.modalias: dict[str, str] = {}     # local name -> module
        self.parent_func: dict[int, ast.AST | None] = {}
        self.defs_in: dict[int | None, dict[str, ast.FunctionDef]] = {}
        self.qualname: dict[int, str] = {}
        self._build()

    def _build(self):
        mi = self.mi
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    if target.split(".")[0] in EXTERNAL_ROOTS:
                        self.alias[name] = target
                    if target in self.idx.modules:
                        self.modalias[name] = target
            elif isinstance(node, ast.ImportFrom):
                from .core import _resolve_import
                targets = _resolve_import(mi.name, mi.is_pkg, node)
                base = targets[0] if targets else ""
                for a in node.names:
                    local = a.asname or a.name
                    full = f"{base}.{a.name}" if base else a.name
                    if any(base == r or base.startswith(r + ".")
                           for r in EXTERNAL_ROOTS):
                        self.alias[local] = full
                    if full in self.idx.modules:
                        self.modalias[local] = full
                    elif base in self.idx.modules:
                        self.funcimports[local] = (base, a.name)
        # lexical function scopes + qualnames
        def visit(node, parent, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    self.parent_func[id(child)] = parent
                    self.defs_in.setdefault(
                        id(parent) if parent is not None else None,
                        {})[child.name] = child
                    self.qualname[id(child)] = qn
                    visit(child, child, qn + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, parent, f"{prefix}{child.name}.")
                else:
                    visit(child, parent, prefix)
        visit(self.mi.tree, None, "")

    def canon(self, node: ast.AST) -> str | None:
        """Canonical dotted path of an expression through import aliases
        (``jnp.sum`` -> ``jax.numpy.sum``)."""
        d = dotted_name(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        root = self.alias.get(head)
        if root is None:
            return d
        return f"{root}.{rest}" if rest else root

    def resolve(self, scope: ast.AST | None, expr: ast.AST):
        """Resolve a function reference to ``(modctx_key, funcdef)`` —
        same-module defs through the lexical chain, then ``from``-imports,
        then ``module.attr`` via module aliases."""
        if isinstance(expr, ast.Name):
            cur = scope
            while True:
                defs = self.defs_in.get(id(cur) if cur is not None
                                        else None, {})
                if expr.id in defs:
                    return (self.mi.name, defs[expr.id])
                if cur is None:
                    break
                cur = self.parent_func.get(id(cur))
            if expr.id in self.funcimports:
                mod, fn = self.funcimports[expr.id]
                return ("import", (mod, fn))
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            mod = self.modalias.get(expr.value.id)
            if mod is not None:
                return ("import", (mod, expr.attr))
        return None


def _is_jaxy(dotted: str | None) -> bool:
    return dotted is not None and (dotted == "jax"
                                   or dotted.startswith("jax."))


def _static_params(call_kwargs, func: ast.FunctionDef) -> set[str]:
    """Parameter names made static by static_argnums/static_argnames."""
    out: set[str] = set()
    args = func.args
    pos = [a.arg for a in args.posonlyargs + args.args]
    for kw in call_kwargs:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) \
                        and isinstance(n.value, int) \
                        and not isinstance(n.value, bool):
                    if 0 <= n.value < len(pos):
                        out.add(pos[n.value])
    return out


def _arrayish_annotation(ann: ast.AST | None) -> bool:
    """True when the annotation could denote an array/pytree (stays
    traced); a plainly scalar/config annotation makes the param static."""
    if ann is None:
        return True  # unannotated: assume traced
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return any(tok in ann.value for tok in ARRAYISH_ANN)
    for n in ast.walk(ann):
        t = terminal_name(n)
        if t is not None and t in ARRAYISH_ANN:
            return True
    return False


def _initial_taint(func: ast.FunctionDef, statics: set[str]) -> set[str]:
    args = func.args
    tainted: set[str] = set()
    named = args.posonlyargs + args.args + args.kwonlyargs
    defaults = dict(zip([a.arg for a in args.args[::-1]],
                        [d for d in args.defaults[::-1]]))
    for a in args.kwonlyargs:
        d = args.kw_defaults[args.kwonlyargs.index(a)]
        if d is not None:
            defaults[a.arg] = d
    for i, a in enumerate(named):
        if a.arg in statics:
            continue
        if i == 0 and a.arg in ("self", "cls"):
            continue
        d = defaults.get(a.arg)
        if isinstance(d, ast.Constant) and d.value is not None:
            continue  # literal config default -> treated static
        if a.annotation is not None \
                and not _arrayish_annotation(a.annotation):
            continue  # int/float/Config-style annotation -> static
        tainted.add(a.arg)
    if args.vararg is not None:
        tainted.add(args.vararg.arg)
    return tainted


def _isinstance_names(test: ast.AST) -> set[str]:
    """Names whose type is being inspected anywhere in a test — the
    ``isinstance(x, jax.core.Tracer)`` host-guard idiom un-taints them."""
    out: set[str] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Call) \
                and terminal_name(n.func) == "isinstance" \
                and n.args and isinstance(n.args[0], ast.Name):
            out.add(n.args[0].id)
    return out


def _handles_tracer_error(handlers) -> bool:
    for h in handlers:
        if h.type is None:
            return True  # bare except swallows the concretization too
        for n in ast.walk(h.type):
            t = terminal_name(n)
            if t and any(m in t for m in TRACER_EXC_MARKERS):
                return True
    return False


class _FuncChecker:
    def __init__(self, ctx: ModCtx, func: ast.FunctionDef,
                 statics: set[str], findings: list[Finding]):
        self.ctx = ctx
        self.func = func
        self.findings = findings
        self.scope_name = ctx.qualname.get(id(func), func.name)
        self.tainted = _initial_taint(func, statics)
        self.suppress = 0

    # -- taint ------------------------------------------------------------

    def is_tainted(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in UNTAINT_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self.is_tainted(node.left)
                    or any(self.is_tainted(c) for c in node.comparators))
        if isinstance(node, ast.Call):
            dotted = self.ctx.canon(node.func)
            if dotted in JAXY_STATIC:
                return False
            if _is_jaxy(dotted):
                return True
            t = terminal_name(node.func)
            if t in SAFE_CALLS or t in CAST_CALLS or t in ITEM_METHODS:
                return False
            if isinstance(node.func, ast.Attribute):
                # method call: taint flows through the receiver — x.sum()
                # is traced, config.with_resolved(...) is config (even
                # when handed a traced arg it only inspects metadata)
                return self.is_tainted(node.func.value)
            return (any(self.is_tainted(a) for a in node.args)
                    or any(self.is_tainted(k.value) for k in node.keywords))
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return False
        return any(self.is_tainted(c) for c in ast.iter_child_nodes(node))

    # -- findings ---------------------------------------------------------

    def flag(self, rule: str, node: ast.AST, message: str, detail: str):
        if self.suppress:
            return
        self.findings.append(Finding(
            pass_id=PASS_ID, rule=rule, path=self.ctx.mi.rel,
            line=getattr(node, "lineno", 0),
            scope=f"{self.ctx.mi.name}:{self.scope_name}",
            message=message, detail=detail,
        ))

    def scan_expr(self, node: ast.AST):
        """Flag violating calls anywhere inside an expression."""
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(n, ast.Call):
                continue
            dotted = self.ctx.canon(n.func)
            t = terminal_name(n.func)
            if dotted is not None:
                if dotted.startswith("time."):
                    self.flag("TRC006", n,
                              f"{dotted}() inside traced code runs once "
                              "at trace time (timings are baked into the "
                              "compiled program)", dotted)
                    continue
                if dotted.startswith("random.") \
                        or dotted.startswith("numpy.random."):
                    self.flag("TRC007", n,
                              f"{dotted}() inside traced code draws once "
                              "at trace time; thread a jax PRNG key "
                              "instead", dotted)
                    continue
                if dotted.startswith("numpy.") and (
                        any(self.is_tainted(a) for a in n.args)
                        or any(self.is_tainted(k.value)
                               for k in n.keywords)):
                    self.flag("TRC004", n,
                              f"{dotted}() applied to a traced value "
                              "(host numpy cannot consume tracers; use "
                              "jnp)", dotted)
                    continue
            if isinstance(n.func, ast.Name) and n.func.id == "print":
                self.flag("TRC005", n,
                          "print() inside traced code runs at trace time "
                          "only; use jax.debug.print", "print")
                continue
            if t in CAST_CALLS and any(self.is_tainted(a)
                                       for a in n.args):
                self.flag("TRC003", n,
                          f"{t}() on a traced value concretizes the "
                          "tracer (ConcretizationTypeError / host sync)",
                          f"{t}()")
                continue
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ITEM_METHODS \
                    and self.is_tainted(n.func.value):
                self.flag("TRC003", n,
                          f".{n.func.attr}() on a traced value forces a "
                          "host transfer inside the trace",
                          f".{n.func.attr}()")

    # -- statement walk ---------------------------------------------------

    def assign_target(self, target: ast.AST, tainted: bool):
        if isinstance(target, ast.Name):
            (self.tainted.add if tainted
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign_target(elt, tainted)
        elif isinstance(target, ast.Starred):
            self.assign_target(target.value, tainted)

    def exec_block(self, stmts):
        for s in stmts:
            self.exec_stmt(s)

    def exec_stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return  # nested defs analyzed separately via the worklist
        if isinstance(s, ast.Assign):
            self.scan_expr(s.value)
            t = self.is_tainted(s.value)
            for target in s.targets:
                self.assign_target(target, t)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.scan_expr(s.value)
                self.assign_target(s.target, self.is_tainted(s.value))
        elif isinstance(s, ast.AugAssign):
            self.scan_expr(s.value)
            if isinstance(s.target, ast.Name):
                if self.is_tainted(s.value) or self.is_tainted(s.target):
                    self.tainted.add(s.target.id)
        elif isinstance(s, ast.If) or isinstance(s, ast.While):
            guard_raise = (isinstance(s, ast.If) and not s.orelse
                           and s.body
                           and all(isinstance(b, ast.Raise)
                                   for b in s.body))
            if guard_raise:
                # validation guard: failing loudly at trace time is the
                # point — neither the branch nor its test is a finding
                self.suppress += 1
                self.scan_expr(s.test)
                self.suppress -= 1
            else:
                self.scan_expr(s.test)
                if self.is_tainted(s.test):
                    kind = "if" if isinstance(s, ast.If) else "while"
                    self.flag("TRC001", s,
                              f"Python `{kind}` on a traced value (use "
                              "jnp.where / lax.cond / lax.while_loop)",
                              kind)
            checked = {n for n in _isinstance_names(s.test)
                       if n in self.tainted}
            self.tainted -= checked
            before = set(self.tainted)
            self.exec_block(s.body)
            after_body = set(self.tainted)
            self.tainted = set(before)
            self.exec_block(s.orelse)
            self.tainted |= after_body
            self.tainted |= checked
        elif isinstance(s, ast.Assert):
            self.scan_expr(s.test)
            if self.is_tainted(s.test):
                self.flag("TRC002", s,
                          "assert on a traced value (silently ignored "
                          "under jit or a concretization error; use "
                          "checkify or static shape checks)", "assert")
        elif isinstance(s, ast.For):
            self.scan_expr(s.iter)
            self.assign_target(s.target, self.is_tainted(s.iter))
            self.exec_block(s.body)
            self.exec_block(s.orelse)
        elif isinstance(s, ast.With):
            for item in s.items:
                self.scan_expr(item.context_expr)
                if item.optional_vars is not None:
                    self.assign_target(item.optional_vars,
                                       self.is_tainted(item.context_expr))
            self.exec_block(s.body)
        elif isinstance(s, ast.Try):
            if _handles_tracer_error(s.handlers):
                # the code expects and handles trace-time concretization
                self.suppress += 1
                self.exec_block(s.body)
                self.suppress -= 1
            else:
                self.exec_block(s.body)
            for h in s.handlers:
                self.exec_block(h.body)
            self.exec_block(s.orelse)
            self.exec_block(s.finalbody)
        elif isinstance(s, ast.Raise):
            if s.exc is not None:
                # error-message formatting; a tracer here raises loudly
                # anyway, which is what the raise wants
                self.suppress += 1
                self.scan_expr(s.exc)
                self.suppress -= 1
        elif isinstance(s, (ast.Return, ast.Expr)):
            if s.value is not None:
                self.scan_expr(s.value)
        elif isinstance(s, ast.Delete):
            for target in s.targets:
                if isinstance(target, ast.Name):
                    self.tainted.discard(target.id)

    def run(self):
        self.exec_block(self.func.body)


# -- root discovery & reachability ----------------------------------------


def _decorator_root(func: ast.FunctionDef):
    """(is_traced, statics) from the decorator list."""
    for dec in func.decorator_list:
        t = terminal_name(dec)
        if t in ("jit", "pjit"):
            return True, set()
        if isinstance(dec, ast.Call):
            ct = terminal_name(dec.func)
            if ct in ("jit", "pjit"):
                return True, _static_params(dec.keywords, func)
            if ct == "partial" and dec.args:
                inner = terminal_name(dec.args[0])
                if inner in ("jit", "pjit"):
                    return True, _static_params(dec.keywords, func)
    return False, set()


def _callsite_statics(call: ast.Call, callee: ast.FunctionDef,
                      checker: _FuncChecker) -> set[str]:
    """Callee params whose matching call-site argument is untainted in
    the caller — host config threaded through the call graph."""
    args = callee.args
    pos = [a.arg for a in args.posonlyargs + args.args]
    statics: set[str] = set()
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(pos) and not checker.is_tainted(a):
            statics.add(pos[i])
    kw_ok = set(pos) | {a.arg for a in args.kwonlyargs}
    for kw in call.keywords:
        if kw.arg and kw.arg in kw_ok and not checker.is_tainted(kw.value):
            statics.add(kw.arg)
    return statics


def _spec_literal_axes(exprs) -> set[str] | None:
    """Union of literal axis names spelled inside ``P(...)`` /
    ``PartitionSpec(...)`` calls across the given spec expressions.

    Returns ``None`` (unknown — abstain) when any spec routes an axis
    through a variable/call, or when no spec literal names an axis at
    all: an empty literal set proves nothing about the mesh, only a
    non-empty one gives names to check ``ppermute`` against."""
    axes: set[str] = set()
    for expr in exprs:
        if expr is None:
            continue
        for n in ast.walk(expr):
            if not (isinstance(n, ast.Call)
                    and terminal_name(n.func) in ("P", "PartitionSpec")):
                continue
            for a in list(n.args) + [k.value for k in n.keywords
                                     if k.arg != "unreduced"]:
                for c in ast.walk(a):
                    if isinstance(c, ast.Constant):
                        if isinstance(c.value, str):
                            axes.add(c.value)
                    elif not isinstance(c, (ast.Tuple, ast.List)):
                        return None  # computed axis name -> abstain
    return axes or None


def _ppermute_axis_arg(call: ast.Call):
    """The axis_name operand of a ``ppermute`` call (positional slot 1
    or keyword), or None when absent."""
    if len(call.args) > 1 and not isinstance(call.args[1], ast.Starred):
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    return None


def _check_ppermute_axes(body_ctx, body: ast.AST, axes: set[str],
                         scope_name: str, findings: list[Finding]):
    """TRC008: flag ``ppermute`` calls inside a shard_map body whose
    literal axis_name is not among the call site's literal spec axes."""
    for n in ast.walk(body):
        if not (isinstance(n, ast.Call)
                and terminal_name(n.func) == "ppermute"):
            continue
        arg = _ppermute_axis_arg(n)
        if arg is None:
            findings.append(Finding(
                pass_id=PASS_ID, rule="TRC008", path=body_ctx.mi.rel,
                line=getattr(n, "lineno", 0),
                scope=f"{body_ctx.mi.name}:{scope_name}",
                message="ppermute without an axis_name inside a "
                        "shard_map body (the collective cannot bind to "
                        "a mesh axis)",
                detail="ppermute"))
            continue
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue  # variable axis name: abstain
        if arg.value not in axes:
            named = ", ".join(sorted(axes))
            findings.append(Finding(
                pass_id=PASS_ID, rule="TRC008", path=body_ctx.mi.rel,
                line=getattr(n, "lineno", 0),
                scope=f"{body_ctx.mi.name}:{scope_name}",
                message=f"ppermute over axis '{arg.value}' but the "
                        f"enclosing shard_map's specs only name "
                        f"{{{named}}} (unbound or wrong mesh axis)",
                detail=arg.value))


def run(idx: ProjectIndex) -> list[Finding]:
    findings: list[Finding] = []
    ctxs = {mi.name: ModCtx(mi, idx) for mi in idx.files if mi.name}

    # seed the worklist: (ctx, funcdef, statics)
    work: list[tuple[ModCtx, ast.FunctionDef, set[str]]] = []
    seen: set[tuple[str, int]] = set()

    def enqueue(ctx: ModCtx, func: ast.FunctionDef, statics: set[str]):
        key = (ctx.mi.name, id(func))
        if key in seen:
            return
        seen.add(key)
        work.append((ctx, func, statics))

    def resolved_def(ctx: ModCtx, scope, expr):
        hit = ctx.resolve(scope, expr)
        if hit is None:
            return None
        kind, payload = hit
        if kind == "import":
            mod, fn = payload
            other = ctxs.get(mod)
            if other is None:
                return None
            func = other.defs_in.get(None, {}).get(fn)
            return (other, func) if func is not None else None
        return (ctx, payload)

    def resolve_and_enqueue(ctx: ModCtx, scope, expr, statics: set[str]):
        hit = resolved_def(ctx, scope, expr)
        if hit is not None:
            enqueue(hit[0], hit[1], statics)

    for ctx in ctxs.values():
        # decorated roots
        for node in ast.walk(ctx.mi.tree):
            if isinstance(node, ast.FunctionDef):
                traced, statics = _decorator_root(node)
                if traced:
                    enqueue(ctx, node, statics)
            elif isinstance(node, ast.Call):
                t = terminal_name(node.func)
                if t not in TRACE_ENTRY or not node.args:
                    continue
                scope = _enclosing_function(ctx, node)
                statics = set()
                first = node.args[0]
                if isinstance(first, (ast.Name, ast.Attribute)):
                    # static argnames only apply to the jit family
                    if t in ("jit", "pjit"):
                        hit = resolved_def(ctx, scope, first)
                        if hit is not None:
                            statics = _static_params(node.keywords, hit[1])
                    resolve_and_enqueue(ctx, scope, first, statics)
                elif isinstance(first, ast.Lambda):
                    pass  # lambdas get checked via their parent function
                if t == "shard_map":
                    kwargs = {k.arg: k.value for k in node.keywords}
                    specs = [kwargs.get("in_specs"),
                             kwargs.get("out_specs")]
                    specs += node.args[2:4]  # positional spec slots
                    axes = _spec_literal_axes(specs)
                    if axes is None:
                        continue
                    if isinstance(first, ast.Lambda):
                        body_hit = (ctx, first)
                    else:
                        body_hit = resolved_def(ctx, scope, first)
                    if body_hit is None:
                        continue
                    bctx, body = body_hit
                    sname = bctx.qualname.get(
                        id(body), getattr(body, "name", None))
                    if sname is None:
                        sname = (ctx.qualname.get(id(scope), scope.name)
                                 if scope is not None else "<module>")
                    _check_ppermute_axes(bctx, body, axes, sname,
                                         findings)

    # walk the call graph: any referenced in-project function is traced
    out_findings: list[Finding] = []
    while work:
        ctx, func, statics = work.pop()
        checker = _FuncChecker(ctx, func, statics, out_findings)
        checker.run()
        skip_ids: set[int] = set()
        # the decorator expressions run at def time, on the host
        for dec in func.decorator_list:
            for n in ast.walk(dec):
                skip_ids.add(id(n))
        for n in ast.walk(func):
            if isinstance(n, ast.Call) \
                    and terminal_name(n.func) in CALLBACK_CALLS:
                for a in n.args:
                    skip_ids.add(id(a))
        for n in ast.walk(func):
            if id(n) in skip_ids:
                continue
            if isinstance(n, ast.Call):
                # direct call: propagate which args are host-static
                hit = resolved_def(ctx, _enclosing_function(ctx, n)
                                   or func, n.func)
                if hit is not None:
                    enqueue(hit[0], hit[1],
                            _callsite_statics(n, hit[1], checker))
                    skip_ids.add(id(n.func))
                    continue
            if isinstance(n, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(n, "ctx", None), ast.Load):
                resolve_and_enqueue(ctx, _enclosing_function(ctx, n)
                                    or func, n, set())
        # nested defs inside a traced function body are traced closures
        for child in ast.walk(func):
            if isinstance(child, ast.FunctionDef) and child is not func \
                    and ctx.parent_func.get(id(child)) is func:
                enqueue(ctx, child, set())

    findings.extend(out_findings)
    return findings


def _enclosing_function(ctx: ModCtx, node: ast.AST):
    """Nearest enclosing FunctionDef of a node (via a lazily-built parent
    map per module)."""
    pm = getattr(ctx, "_parents", None)
    if pm is None:
        pm = {}
        for parent in ast.walk(ctx.mi.tree):
            for child in ast.iter_child_nodes(parent):
                pm[id(child)] = parent
        ctx._parents = pm
    cur = pm.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = pm.get(id(cur))
    return None
