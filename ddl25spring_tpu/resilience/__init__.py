"""Fault injection, failure containment, and recovery.

- :mod:`.faults` — seeded deterministic :class:`FaultPlan` (dropout,
  stragglers, corrupted updates, serving stalls, crash points) parsed
  from a compact spec string;
- :mod:`.guard` — jit-side non-finite screening of stacked client
  updates and a host-side :class:`DivergenceGuard` for training loops;
- :mod:`.retry` — bounded retry with exponential backoff + jitter and a
  :class:`Deadline` helper;
- :mod:`.autoresume` — checkpoint-every-round training wrapper that
  resumes bit-exactly after a crash.

See ``docs/RESILIENCE.md`` for the failure model and recipes.
"""

from .faults import FaultPlan, InjectedCrash
from .guard import (DivergenceGuard, ValidationGate, screen_nonfinite,
                    tree_client_isfinite)
from .retry import Deadline, RetryError, backoff_delays, retry_call

__all__ = [
    "FaultPlan",
    "InjectedCrash",
    "DivergenceGuard",
    "ValidationGate",
    "screen_nonfinite",
    "tree_client_isfinite",
    "Deadline",
    "RetryError",
    "backoff_delays",
    "retry_call",
    "run_with_autoresume",
]


def __getattr__(name):
    # autoresume pulls in utils.checkpoint (orbax) — keep that import out
    # of the package's import path so fault/guard users never pay for it
    if name == "run_with_autoresume":
        from .autoresume import run_with_autoresume
        return run_with_autoresume
    raise AttributeError(name)
