"""BPE tokenizer oracles: round-trip, compression, determinism, and exact
Python ≡ C++ equivalence (the same oracle style that pins the native token
stream to its Python twin, SURVEY.md §4)."""

import pytest

from ddl25spring_tpu.data.bpe import BASE_VOCAB, BpeTokenizer
from ddl25spring_tpu.native import (
    bpe_build_error,
    bpe_encode,
    bpe_native_available,
    bpe_train,
)

CORPUS = (
    "once upon a time there was a little robot. the little robot liked "
    "to read stories. once upon a time, said the robot, there was a "
    "little reader who liked robots. the stories were little and the "
    "time was little but the robot read on and on. "
) * 4


@pytest.fixture(scope="module")
def tok():
    # pin the pure-Python trainer: these tests specify ITS behavior, and
    # the native trainer is separately pinned to it in the equivalence test
    return BpeTokenizer.train(CORPUS, vocab_size=BASE_VOCAB + 64,
                              native=False)


def test_bpe_learns_merges_and_compresses(tok):
    assert tok.vocab_size > BASE_VOCAB
    text = "the little robot read stories"
    ids = tok.encode(text, bos=False, eos=False)
    assert len(ids) < len(text.encode())  # merges actually fire
    assert any(i >= BASE_VOCAB for i in ids)


def test_bpe_roundtrip(tok):
    for text in (
        "once upon a time",
        "completely unseen words zyx!",
        "  leading and   multiple   spaces ",
        "unicode: héllo wörld 🤖",
    ):
        ids = tok.encode(text)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        assert tok.decode(ids) == text


def test_bpe_deterministic():
    a = BpeTokenizer.train(CORPUS, vocab_size=BASE_VOCAB + 32, native=False)
    # second run through whichever path auto-select picks: same merges
    b = BpeTokenizer.train(CORPUS, vocab_size=BASE_VOCAB + 32)
    assert a.merges == b.merges


def test_bpe_save_load(tok, tmp_path):
    path = tmp_path / "merges.txt"
    tok.save(path)
    loaded = BpeTokenizer.load(path)
    assert loaded.merges == tok.merges
    text = "the robot read"
    assert loaded.encode(text) == tok.encode(text)


def test_bpe_vocab_too_small_raises():
    with pytest.raises(ValueError, match="vocab_size"):
        BpeTokenizer.train(CORPUS, vocab_size=100)


def test_bpe_empty_and_degenerate():
    tok = BpeTokenizer.train("aa bb aa", vocab_size=BASE_VOCAB + 8,
                             native=False)
    assert tok.decode(tok.encode("")) == ""
    assert tok.encode("", bos=False, eos=False) == []


def test_run_lm_bpe_tokenizer_converges():
    """The LM runner trains against a BPE-trained vocab end-to-end (the
    reference's SPTokenizer wiring, primer/intro.py:15-18)."""
    from ddl25spring_tpu.configs import LmConfig
    from ddl25spring_tpu.run_lm import run

    losses = run(LmConfig(strategy="single", tokenizer="bpe",
                          bpe_vocab_size=384, bpe_train_stories=50,
                          batch_size=4, seq_l=32, dmodel=32, nr_heads=2,
                          nr_layers=2, nr_iters=8, lr=3e-3), log_every=7)
    assert losses[-1] < losses[0]


def test_native_bpe_matches_python():
    if not bpe_native_available():
        pytest.skip(f"no native bpe: {bpe_build_error()}")
    vocab = BASE_VOCAB + 48
    py = BpeTokenizer.train(CORPUS, vocab_size=vocab, native=False)
    native_merges = bpe_train(CORPUS.encode(), vocab)
    assert [tuple(m) for m in native_merges.tolist()] == py.merges

    for text in (
        "the little robot read stories",
        "unseen zyx words",
        "once upon a time there was",
        "unicode: héllo 🤖",
    ):
        ids_native = bpe_encode(native_merges, text.encode()).tolist()
        assert ids_native == py.encode(text)
