"""Heart-disease tabular dataset (UCI Cleveland derivative).

The reference uses ``lab/tutorial_2a/heart.csv`` (1025 rows) for the
centralized classifier (centralized.py:32), the tabular VAE
(generative-modeling.py:133-140) and all VFL experiments (vfl.py:108).
We load the same CSV when present (the read-only reference mount or
``$DDL25_DATA_DIR``), else generate a deterministic synthetic table with the
same schema: 5 numeric + 8 categorical feature columns + binary ``target``.

Preprocessing mirrors the reference pipelines:
- one-hot encode the categorical columns (pandas ``get_dummies``,
  centralized.py:33-34) → 30 feature columns total for the standard CSV;
- MinMax scaling of numerics for the classifier/VFL path (vfl.py:111),
  StandardScaler over everything for the VAE path (generative-modeling.py:141).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path

import numpy as np
import pandas as pd

CATEGORICAL = ["sex", "cp", "fbs", "restecg", "exang", "slope", "ca", "thal"]
NUMERICAL = ["age", "trestbps", "chol", "thalach", "oldpeak"]
# cardinalities of the categorical columns in the real CSV
_CARDINALITIES = {
    "sex": 2, "cp": 4, "fbs": 2, "restecg": 3,
    "exang": 2, "slope": 3, "ca": 5, "thal": 4,
}


def _candidate_paths():
    env = os.environ.get("DDL25_DATA_DIR")
    if env:
        yield Path(env) / "heart.csv"
    yield Path.home() / ".cache" / "ddl25spring" / "heart.csv"
    yield Path("/root/reference/lab/tutorial_2a/heart.csv")
    yield Path("/root/reference/lab/tutorial_2b/heart-dataset/heart.csv")


def synthetic_heart_df(n: int = 1025, seed: int = 7) -> pd.DataFrame:
    """Deterministic table with the heart.csv schema and a learnable target."""
    rng = np.random.default_rng(seed)
    df = pd.DataFrame()
    df["age"] = rng.integers(29, 78, n)
    df["trestbps"] = rng.integers(94, 201, n)
    df["chol"] = rng.integers(126, 565, n)
    df["thalach"] = rng.integers(71, 203, n)
    df["oldpeak"] = np.round(rng.uniform(0, 6.2, n), 1)
    for col, card in _CARDINALITIES.items():
        df[col] = rng.integers(0, card, n)
    # target correlated with a few features so classifiers have signal
    logit = (
        0.04 * (df["thalach"] - 150)
        - 0.03 * (df["age"] - 54)
        - 0.8 * (df["exang"])
        + 0.5 * (df["cp"] > 0).astype(float)
        - 0.7 * (df["oldpeak"] - 1)
    )
    p = 1 / (1 + np.exp(-logit))
    df["target"] = (rng.uniform(size=n) < p).astype(np.int64)
    return df


def load_heart_df() -> tuple[pd.DataFrame, bool]:
    """Return (dataframe, synthetic flag)."""
    from .mnist import announce_synthetic_fallback

    for p in _candidate_paths():
        if p.exists():
            return pd.read_csv(p), False
    announce_synthetic_fallback("heart")
    return synthetic_heart_df(), True


def one_hot_encode(df: pd.DataFrame) -> pd.DataFrame:
    """One-hot the categorical columns; keeps column-name convention
    ``<col>_<value>`` used by the reference's per-client feature expansion
    (vfl.py:131-139)."""
    return pd.get_dummies(df, columns=CATEGORICAL)


@dataclass
class HeartData:
    x: np.ndarray            # (n, d) float32 features
    y: np.ndarray            # (n,) int32 labels
    feature_names: list      # length d, post-one-hot
    synthetic: bool


def load_heart_classification(minmax: bool = True) -> HeartData:
    """One-hot + (optionally) MinMax-scaled features, int labels."""
    df, synthetic = load_heart_df()
    encoded = one_hot_encode(df)
    x_df = encoded.drop(columns=["target"])
    x = x_df.to_numpy(dtype=np.float32)
    if minmax:
        lo, hi = x.min(axis=0), x.max(axis=0)
        x = (x - lo) / np.maximum(hi - lo, 1e-8)
    y = encoded["target"].to_numpy(dtype=np.int32)
    return HeartData(x=x, y=y, feature_names=list(x_df.columns), synthetic=synthetic)
