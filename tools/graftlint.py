"""graftlint CLI — run the ddl25spring_tpu static contract passes.

Usage:
    python tools/graftlint.py                       # lint ddl25spring_tpu
    python tools/graftlint.py ddl25spring_tpu/fl    # subtree only
    python tools/graftlint.py --json                # machine-readable
    python tools/graftlint.py --passes determinism,donation-safety
    python tools/graftlint.py --write-baseline      # accept current state
    python tools/graftlint.py --no-baseline         # raw findings

Exit codes: 0 — clean (every finding baselined, no stale baseline
entries); 1 — non-baselined findings (or stale baseline entries naming
findings that no longer exist); 2 — usage/configuration errors (bad
baseline file, unknown pass, unparseable source).

The JSON document is a stable contract (tests/test_analysis.py pins it):

    {"version": 1,
     "passes": ["import-purity", ...],
     "findings": [{"id", "pass", "rule", "path", "line", "scope",
                   "message", "detail", "baselined", "justification"?}],
     "summary": {"total", "baselined", "new", "stale_baseline"}}

Baselining: ``--write-baseline`` rewrites the baseline with *all*
current findings, carrying existing justifications over and leaving new
entries' justifications empty — fill each one in by hand; the loader
rejects empty justifications, so an unexplained entry cannot ship.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from ddl25spring_tpu.analysis import (  # noqa: E402
    PASS_ORDER,
    BaselineError,
    load_baseline,
    render_baseline,
    run_passes,
)

JSON_VERSION = 1
DEFAULT_BASELINE = REPO_ROOT / "tools" / "graftlint_baseline.json"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="static trace-hygiene / determinism / contract "
                    "analyzer for the ddl25spring_tpu tree")
    ap.add_argument("paths", nargs="*", type=Path,
                    default=[REPO_ROOT / "ddl25spring_tpu"],
                    help="files or directories to lint "
                         "(default: ddl25spring_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the JSON document instead of human output")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help="baseline file of accepted findings "
                         "(default: tools/graftlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(carries over existing justifications)")
    ap.add_argument("--passes", type=str, default=None,
                    help="comma-separated subset of: "
                         + ", ".join(PASS_ORDER))
    args = ap.parse_args(argv)

    passes = None
    if args.passes:
        passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())

    try:
        findings = run_passes(list(args.paths), REPO_ROOT, passes)
    except (ValueError, OSError, BaselineError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2

    baseline: dict[str, dict] = {}
    if not args.no_baseline and args.baseline.exists():
        try:
            baseline = load_baseline(args.baseline)
        except (BaselineError, json.JSONDecodeError) as e:
            print(f"graftlint: error: {e}", file=sys.stderr)
            return 2

    if args.write_baseline:
        args.baseline.write_text(render_baseline(findings, baseline))
        blank = sum(1 for f in findings
                    if not baseline.get(f.id, {}).get("justification"))
        print(f"graftlint: wrote {args.baseline} "
              f"({len(findings)} entries, {blank} needing a "
              "justification)")
        return 0

    for f in findings:
        entry = baseline.get(f.id)
        if entry is not None:
            f.baselined = True
            f.justification = str(entry.get("justification", ""))
    current_ids = {f.id for f in findings}
    stale = sorted(fid for fid in baseline if fid not in current_ids)
    new = [f for f in findings if not f.baselined]

    doc = {
        "version": JSON_VERSION,
        "passes": list(passes or PASS_ORDER),
        "findings": [f.to_json() for f in findings],
        "summary": {
            "total": len(findings),
            "baselined": len(findings) - len(new),
            "new": len(new),
            "stale_baseline": len(stale),
        },
    }
    if args.as_json:
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            mark = "baselined" if f.baselined else "NEW"
            print(f"{f.path}:{f.line}: [{f.rule}] {f.message} "
                  f"({f.id}, {mark})")
        for fid in stale:
            print(f"{args.baseline.name}: stale baseline entry {fid} "
                  "(finding no longer produced — remove it)")
        s = doc["summary"]
        print(f"graftlint: {s['total']} finding(s): {s['new']} new, "
              f"{s['baselined']} baselined, {s['stale_baseline']} stale "
              "baseline entr(ies)")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
