"""ZeRO-style weight-update sharding for data parallelism.

Implements the technique of "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (Xu et al., 2020; the ZeRO-1 idea, listed
in PAPERS.md): plain DP replicates the optimizer state and applies the same
weight update on every replica, wasting W-1 copies of memory and compute.
Here each device owns a 1/W slice of the flattened parameter vector:

- per-shard gradients are combined with ``psum_scatter`` (each device
  receives only ITS slice of the summed gradient — half the collective
  bytes of a full all-reduce);
- the optimizer update runs on the slice (optimizer state lives sharded:
  the Adam moments for 1/W of the params per device);
- the updated slices are re-assembled with ``all_gather``.

psum_scatter + all_gather together move the same bytes as the all_reduce
they replace, so there is no communication regret — but optimizer state
memory and update FLOPs drop by W.  The reference has no analogue (its DP
keeps a full optimizer per process, intro_DP_GA.py:67); this is what the
same algorithm looks like designed for a TPU mesh.

The math is element-for-element identical to unsharded DP for any
elementwise optax optimizer (SGD/momentum/Adam/...), which is the test
oracle (tests/test_zero.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from .compat import shard_map
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P


def _check_elementwise(optimizer, W: int, probe_per_shard: int = 4):
    """ZeRO sharding is only exact for elementwise optimizers (each
    coordinate's update depends on that coordinate's gradient/params
    history alone — SGD, momentum, Adam, ...).  A cross-coordinate
    transform like ``clip_by_global_norm`` would clip per-slice norms and
    silently diverge from plain DP, so probe at build time: updating a
    small vector whole must equal updating it slice-by-slice."""
    k = probe_per_shard
    # several steps with varying gradients: a single step cannot expose
    # cross-coordinate transforms behind a normalising optimizer (Adam's
    # first step is scale-invariant, so per-slice clipping hides), but the
    # scale sequence enters the moments and diverges by step 2
    grad_seq = [
        jnp.sin(jnp.arange(W * k, dtype=jnp.float32) + 1.7 * t)
        for t in range(3)
    ]
    p0 = jnp.linspace(0.5, -0.5, W * k, dtype=jnp.float32)

    def run(gs, p):
        state = optimizer.init(p)
        for g in gs:
            updates, state = optimizer.update(g, state, p)
            p = optax.apply_updates(p, updates)
        return p

    whole = run(grad_seq, p0)
    pieces = [
        run([g[i * k:(i + 1) * k] for g in grad_seq], p0[i * k:(i + 1) * k])
        for i in range(W)
    ]
    if not jnp.allclose(whole, jnp.concatenate(pieces), atol=1e-6):
        raise ValueError(
            "optimizer is not elementwise (its update mixes coordinates, "
            "e.g. global-norm clipping), so ZeRO weight-update sharding "
            "would silently change the training dynamics; use "
            "make_dp_train_step for this optimizer"
        )


def make_zero_dp_train_step(loss_fn, optimizer, mesh, params,
                            axis: str = "data", donate: bool = False):
    """Build the ZeRO-sharded DP trainer for the given ``params`` structure.

    Returns ``(step, opt_state)`` where ``opt_state`` is the SHARDED
    optimizer state (leaves carry a leading ``(W, ...)`` shard axis, placed
    with ``P(axis)``) and ``step(params, opt_state, batch) -> (params,
    opt_state, loss)`` is the jitted SPMD step; ``batch`` is globally
    (B, ...) sharded over ``axis``, ``params`` replicated.
    """
    W = mesh.shape[axis]
    _check_elementwise(optimizer, W)
    flat0, unravel = ravel_pytree(params)
    n = flat0.size
    pad = (-n) % W
    chunk = (n + pad) // W

    # sharded optimizer state: init each shard's state from ITS param slice
    # (some elementwise optimizers store params in init(), e.g. lookahead —
    # a zero-vector init would silently diverge from plain DP), then place
    # the leading shard axis on the mesh; scalar leaves (step counters) are
    # identical across shards and stay replicated
    ref_state = optimizer.init(jnp.zeros((chunk,), flat0.dtype))
    p_slices = jnp.pad(flat0, (0, pad)).reshape(W, chunk)
    stacked_state = jax.vmap(optimizer.init)(p_slices)

    def place(ref, leaf):
        if jnp.asarray(ref).ndim == 0:
            return leaf[0]
        return jax.device_put(leaf, NamedSharding(mesh, P(axis)))

    opt_state0 = jax.tree.map(place, ref_state, stacked_state)
    state_spec = jax.tree.map(
        lambda leaf: P(axis) if jnp.asarray(leaf).ndim else P(), ref_state
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), state_spec, P(axis)),
        out_specs=(P(), state_spec, P()),
        check_vma=False,
    )
    def spmd_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        g = ravel_pytree(grads)[0]
        g = jnp.pad(g, (0, pad))
        # each device receives only its slice of the summed gradient
        g_local = jax.lax.psum_scatter(g, axis, tiled=True) / W

        idx = jax.lax.axis_index(axis)
        p_flat = jnp.pad(ravel_pytree(params)[0], (0, pad))
        p_local = jax.lax.dynamic_slice_in_dim(p_flat, idx * chunk, chunk)

        local_state = jax.tree.map(
            lambda leaf: leaf[0] if leaf.ndim else leaf, opt_state
        )
        updates, local_state = optimizer.update(g_local, local_state, p_local)
        p_local = optax.apply_updates(p_local, updates)
        opt_state = jax.tree.map(
            lambda leaf: leaf[None] if leaf.ndim else leaf, local_state
        )

        p_full = jax.lax.all_gather(p_local, axis, tiled=True)
        params = unravel(p_full[:n])
        return params, opt_state, jax.lax.pmean(loss, axis)

    step = jax.jit(spmd_step, donate_argnums=(0, 1) if donate else ())
    return step, opt_state0


def make_zero_server_step(optimizer, mesh, params, axis: str = "clients",
                          donate: bool = False):
    """ZeRO-sharded FEDERATED server update: the FedOpt family treats the
    round's aggregate as a pseudo-gradient ``Δ = params − w_avg`` and runs
    a server optimizer on it (``servers.FedOptServer``).  Plain FedOpt
    replicates the Adam/Yogi moments and the update on every replica of
    the clients mesh; here — the same move as :func:`make_zero_dp_train_step`
    — each replica owns a 1/W slice of the flattened parameter vector, so
    server-optimizer moment memory and update FLOPs drop by W.

    Returns ``(server_step, opt_state)``: ``opt_state`` is the SHARDED
    state (array leaves carry a leading ``(W, ...)`` shard axis placed
    with ``P(axis)``, scalar step counters replicated) and
    ``server_step(params, opt_state, w_avg) -> (params, opt_state)`` is
    the jitted SPMD step — the drop-in signature of FedOptServer's
    replicated ``server_step``.

    Exactness: Δ enters replicated, so ``psum_scatter(Δ)/W`` hands each
    shard ``W·Δ_slice / W`` — bitwise ``Δ_slice`` for power-of-two W
    (float scaling by 2^k is lossless), keeping the element-for-element
    identity with the replicated optimizer that ``_check_elementwise``
    guarantees for the slice-wise update itself (tests/test_zero.py's
    oracle discipline).  The scatter+gather pair moves the same bytes as
    the all-reduce it replaces — no communication regret."""
    W = mesh.shape[axis]
    _check_elementwise(optimizer, W)
    flat0, unravel = ravel_pytree(params)
    n = flat0.size
    pad = (-n) % W
    chunk = (n + pad) // W

    # sharded server-optimizer state, init per slice (the DP builder's
    # reasoning: some elementwise optimizers store params in init())
    ref_state = optimizer.init(jnp.zeros((chunk,), flat0.dtype))
    p_slices = jnp.pad(flat0, (0, pad)).reshape(W, chunk)
    stacked_state = jax.vmap(optimizer.init)(p_slices)

    def place(ref, leaf):
        if jnp.asarray(ref).ndim == 0:
            return leaf[0]
        return jax.device_put(leaf, NamedSharding(mesh, P(axis)))

    opt_state0 = jax.tree.map(place, ref_state, stacked_state)
    state_spec = jax.tree.map(
        lambda leaf: P(axis) if jnp.asarray(leaf).ndim else P(), ref_state
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), state_spec, P()),
        out_specs=(P(), state_spec),
        check_vma=False,
    )
    def spmd_step(params, opt_state, w_avg):
        d = ravel_pytree(params)[0] - ravel_pytree(w_avg)[0]
        d = jnp.pad(d, (0, pad))
        # each replica receives only its slice of the pseudo-gradient
        d_local = jax.lax.psum_scatter(d, axis, tiled=True) / W

        idx = jax.lax.axis_index(axis)
        p_flat = jnp.pad(ravel_pytree(params)[0], (0, pad))
        p_local = jax.lax.dynamic_slice_in_dim(p_flat, idx * chunk, chunk)

        local_state = jax.tree.map(
            lambda leaf: leaf[0] if leaf.ndim else leaf, opt_state
        )
        updates, local_state = optimizer.update(
            d_local, local_state, p_local
        )
        p_local = optax.apply_updates(p_local, updates)
        opt_state = jax.tree.map(
            lambda leaf: leaf[None] if leaf.ndim else leaf, local_state
        )

        p_full = jax.lax.all_gather(p_local, axis, tiled=True)
        return unravel(p_full[:n]), opt_state

    step = jax.jit(spmd_step, donate_argnums=(0, 1) if donate else ())
    return step, opt_state0
