"""Adapter residency pool oracle (models/adapter_pool.py).

The pool is the KV page pool's residency model re-used one level up —
slots instead of pages, tenants instead of streams — so its whole
contract is host-checkable by value, no jax required:

- slot 0 is reserved for the null adapter (acquire(0) never takes a
  slot or a refcount),
- a resident tenant's acquire is a HIT (no install); a cold tenant's
  acquire is a MISS that hands back the store entry to install, after
  LRU-evicting a cold unpinned victim when the pool is full,
- refcounts and pins make a slot ineligible for eviction; with every
  slot busy/pinned ``acquire`` returns None (the admission queues),
- ``adapter_bytes`` is the analytic HBM cost of the stacks, linear in
  the slot count and zero whenever rank or slots are zero.
"""

import dataclasses

import pytest

from ddl25spring_tpu.models.adapter_pool import AdapterPool, adapter_bytes
from ddl25spring_tpu.models.llama import LlamaConfig


def _pool(nr_slots=3, tenants=()):
    pool = AdapterPool(nr_slots)
    for t in tenants:
        pool.put(t, {"fake": t}, 1.0, round_ix=0)
    return pool


# -- construction & registration -------------------------------------------


@pytest.mark.parametrize("bad", [0, 1, -2])
def test_pool_needs_null_plus_one_tenant_slot(bad):
    with pytest.raises(ValueError, match="slot 0"):
        AdapterPool(bad)


def test_put_rejects_the_null_tenant():
    with pytest.raises(ValueError, match="reserved null adapter"):
        _pool().put(0, {"fake": 0}, 1.0)


def test_acquire_unregistered_tenant_raises():
    with pytest.raises(KeyError, match="not registered"):
        _pool().acquire(9)


def test_null_adapter_needs_no_slot_and_no_refcount():
    pool = _pool()
    assert pool.acquire(0) == (0, None)
    assert pool.describe()["refs"] == {}
    pool.release(0)                                # no-op, never raises
    assert pool.can_admit(0)


# -- hit / miss / refcount flow --------------------------------------------


def test_cold_acquire_is_a_miss_that_hands_back_the_store_entry():
    pool = _pool(tenants=[1])
    slot, entry = pool.acquire(1)
    assert slot == 1
    assert entry == ({"fake": 1}, 1.0, 0)          # caller must install
    assert (pool.misses, pool.installs, pool.evictions) == (1, 1, 0)
    # second stream on the same tenant: a hit, nothing to install
    slot2, entry2 = pool.acquire(1)
    assert (slot2, entry2) == (1, None)
    assert pool.misses == 1
    assert pool.describe()["refs"] == {1: 2}
    pool.release(1)
    pool.release(1)
    assert pool.describe()["refs"] == {}
    assert pool.resident(1)                        # release keeps residency


def test_release_errors():
    pool = _pool(tenants=[1])
    with pytest.raises(ValueError, match="not resident"):
        pool.release(1)                            # never acquired
    pool.acquire(1)
    pool.release(1)
    with pytest.raises(ValueError, match="refcount"):
        pool.release(1)                            # refcount already zero


# -- eviction: LRU over cold unpinned slots --------------------------------


def test_lru_eviction_of_the_coldest_tenant():
    pool = _pool(3, tenants=[1, 2, 3])             # 2 tenant slots
    pool.acquire(1)
    pool.acquire(2)
    pool.release(1)
    pool.release(2)
    pool.acquire(1)                                # touch 1: now 2 is LRU
    pool.release(1)
    slot, entry = pool.acquire(3)
    assert slot == pool.slot_of(3)
    assert entry == ({"fake": 3}, 1.0, 0)
    assert not pool.resident(2)                    # the LRU victim
    assert pool.resident(1)
    assert pool.evictions == 1
    # the evicted tenant's return is itself a miss (re-fetch + install)
    misses0 = pool.misses
    pool.release(3)
    _, entry = pool.acquire(2)
    assert entry is not None
    assert pool.misses == misses0 + 1


def test_busy_slots_are_not_evictable():
    pool = _pool(3, tenants=[1, 2, 3])
    pool.acquire(1)
    pool.acquire(2)                                # both slots refcounted
    assert not pool.can_admit(3)
    assert pool.acquire(3) is None                 # admission stays queued
    assert not pool.resident(3)
    pool.release(2)
    assert pool.can_admit(3)
    slot, entry = pool.acquire(3)                  # evicts cold 2, not busy 1
    assert entry is not None
    assert pool.resident(1) and not pool.resident(2)


def test_pin_exempts_from_eviction_and_unpin_restores():
    pool = _pool(3, tenants=[1, 2, 3])
    pool.acquire(1)
    pool.release(1)
    pool.acquire(2)
    pool.release(2)                                # 1 is LRU and cold
    pool.pin(1)
    pool.acquire(3)                                # must evict 2, not pinned 1
    assert pool.resident(1) and not pool.resident(2)
    pool.release(3)
    pool.pin(3)
    assert pool.acquire(2) is None                 # everything pinned
    pool.unpin(3)
    assert pool.acquire(2) is not None
    with pytest.raises(ValueError, match="not resident"):
        pool.pin(9)
    pool.unpin(9)                                  # unpin is forgiving


# -- seeding (rollout-plane replicas come up pre-installed) ----------------


def test_seed_marks_resident_without_an_install():
    pool = _pool(3, tenants=[1])
    pool.seed(1, 2)
    assert pool.slot_of(1) == 2
    assert pool.installs == 0
    slot, entry = pool.acquire(1)
    assert (slot, entry) == (2, None)              # a hit, nothing installed
    assert pool.misses == 0


def test_seed_conflicts_raise():
    pool = _pool(4)
    pool.seed(1, 1)
    with pytest.raises(ValueError, match="already resident"):
        pool.seed(1, 2)                            # tenant already resident
    with pytest.raises(ValueError, match="already resident"):
        pool.seed(2, 1)                            # slot already taken
    with pytest.raises(ValueError, match="out of range"):
        pool.seed(3, 0)                            # the null slot
    with pytest.raises(ValueError, match="out of range"):
        pool.seed(3, 4)


def test_describe_is_the_full_residency_picture():
    pool = _pool(3, tenants=[1, 2])
    pool.acquire(1)
    pool.pin(1)
    d = pool.describe()
    assert d == {"nr_slots": 3, "resident": {1: 1}, "refs": {1: 1},
                 "pinned": [1], "store_tenants": [1, 2],
                 "misses": 1, "evictions": 0, "installs": 1}


# -- adapter_bytes: the analytic HBM cost ----------------------------------

CFG = LlamaConfig(vocab_size=128, dmodel=48, nr_heads=4, nr_kv_heads=2,
                  nr_layers=2, ctx_size=48)


def test_adapter_bytes_zero_without_rank_or_slots():
    assert adapter_bytes(CFG) == 0                          # lora_slots=0
    assert adapter_bytes(CFG, nr_slots=4) == 0              # lora_rank=0
    lora = dataclasses.replace(CFG, lora_rank=4)
    assert adapter_bytes(lora) == 0
    assert adapter_bytes(lora, nr_slots=0) == 0


def test_adapter_bytes_matches_the_site_list_by_hand():
    r, n = 4, 3
    lora = dataclasses.replace(CFG, lora_rank=r)
    d = CFG.dmodel
    kv = CFG.kv_heads * CFG.head_dim
    h = CFG.hidden_dim
    sites = [(d, d), (d, kv), (d, kv), (d, d),
             (d, h), (d, h), (h, d)] * CFG.nr_layers
    sites.append((d, CFG.vocab_size))
    want = n * sum(r * (i + o) * 4 + 4 for i, o in sites)
    assert adapter_bytes(lora, nr_slots=n) == want
    # config-carried lora_slots is the default slot count
    stacked = dataclasses.replace(lora, lora_slots=n)
    assert adapter_bytes(stacked) == want


def test_adapter_bytes_linear_in_slots_and_itemsize():
    lora = dataclasses.replace(CFG, lora_rank=8)
    one = adapter_bytes(lora, nr_slots=1)
    assert adapter_bytes(lora, nr_slots=5) == 5 * one
    assert adapter_bytes(lora, nr_slots=2, itemsize=2) == one  # bf16 halves
