"""(ε, δ) accounting for DP-FedAvg's subsampled Gaussian mechanism.

The reference has no differential privacy at all; this framework's DP-FedAvg
(fl/engine.py: per-client delta clipping + Gaussian noise on the mean) gains
the standard Rényi-DP accountant so a run can REPORT its privacy budget
instead of just its noise knob:

- RDP of the Gaussian mechanism at order α: ``α / (2 σ²)`` (Mironov 2017).
- Client subsampling amplifies privacy: with sampling rate q (the FL
  ``client_fraction``), the per-round RDP at integer order α is bounded by

      1/(α-1) · log Σ_{j=0..α} C(α,j) (1-q)^{α-j} q^j exp(j(j-1)/(2σ²))

  (Mironov-Talwar-Zhang 2019's bound for the Poisson-sampled Gaussian; FL's
  fixed-size-without-replacement sampling is conventionally accounted with
  the same formula — stated here explicitly as the approximation it is).
- Rounds compose additively in RDP; the conversion to (ε, δ) takes the best
  order: ``ε = min_α [ T·RDP(α) + log(1/δ)/(α-1) ]``.

Pure host-side float math (no jax): the accountant runs once per experiment,
not per step.  Everything is computed in log space — the binomial series
overflows float64 by α≈30 otherwise.
"""

from __future__ import annotations

import math

DEFAULT_ORDERS = tuple(range(2, 64)) + (80, 128, 256, 512)


def _log_comb(n: int, k: int) -> float:
    return (
        math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)
    )


def _logsumexp(xs) -> float:
    m = max(xs)
    if m == -math.inf:
        return -math.inf
    return m + math.log(sum(math.exp(x - m) for x in xs))


def rdp_gaussian(alpha: float, noise_mult: float) -> float:
    """RDP of the (unsampled) Gaussian mechanism at order ``alpha``."""
    if noise_mult <= 0:
        raise ValueError("noise_mult must be > 0 for a finite RDP bound")
    return alpha / (2.0 * noise_mult**2)


def rdp_subsampled_gaussian(alpha: int, noise_mult: float, q: float) -> float:
    """Per-round RDP at integer order ``alpha`` with sampling rate ``q``."""
    if noise_mult <= 0:
        # same clean error on every q (the series below would otherwise
        # raise a bare ZeroDivisionError for q < 1)
        raise ValueError("noise_mult must be > 0 for a finite RDP bound")
    if not 0.0 < q <= 1.0:
        raise ValueError(f"sampling rate q must be in (0, 1], got {q}")
    if alpha < 2 or int(alpha) != alpha:
        raise ValueError(f"integer alpha >= 2 required, got {alpha}")
    if q == 1.0:
        return rdp_gaussian(alpha, noise_mult)
    alpha = int(alpha)
    terms = [
        _log_comb(alpha, j)
        + (alpha - j) * math.log1p(-q)
        + j * math.log(q)
        + j * (j - 1) / (2.0 * noise_mult**2)
        for j in range(alpha + 1)
    ]
    return _logsumexp(terms) / (alpha - 1)


def dp_epsilon(
    noise_mult: float,
    q: float,
    rounds: int,
    delta: float,
    orders=DEFAULT_ORDERS,
) -> float:
    """ε of ``rounds`` compositions of the q-subsampled Gaussian at ``δ``.

    ``noise_mult`` is the engine's ``dp_noise_mult`` (σ, in units of the clip
    bound), ``q`` the client sampling rate (``client_fraction``).  Client-
    level DP: one client's entire contribution is the unit of privacy, which
    matches what the engine clips and noises (the per-client delta).
    """
    if rounds < 0:
        raise ValueError(f"rounds must be >= 0, got {rounds}")
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    if rounds == 0:
        return 0.0
    best = math.inf
    for a in orders:
        rdp = rounds * rdp_subsampled_gaussian(int(a), noise_mult, q)
        best = min(best, rdp + math.log(1.0 / delta) / (a - 1))
    return best
